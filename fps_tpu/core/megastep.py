"""Device-resident megastep: K chunks in ONE compiled program.

The per-chunk drivers (``Trainer.fit_stream`` / ``Trainer.run_indexed``)
pay a full host round-trip between compiled calls: Python dispatch, key
folding, metric bookkeeping, and (with syncing consumers) a blocking
device→host transfer sit between every chunk. After PR 10/12 made the
data plane payload-proportional, that host serialization is the last
per-chunk overhead left in the hot loop (ROADMAP: "a fully
device-resident megastep"; automatic cross-replica sharding of weight
updates — arXiv:2004.13336 — shows the win of keeping the whole update
loop on-device).

Here K chunk *segments* run under one ``lax.scan`` step driver inside a
single jitted program with donated table buffers, consuming batches via
the device-side ingest path (:class:`fps_tpu.core.device_ingest.
DeviceEpochPlan`), and the work the host loop used to do at chunk
boundaries happens **in-graph**:

* **reconcile ticks** — every segment ends with the same flush
  reconcile a per-chunk compiled call ends with, so segment boundaries
  hold one canonical table and the megastep is bit-identical to K
  per-chunk ``run_indexed`` dispatches (tested);
* **sketch folds** — each segment's count-min window accumulator is
  psum-merged into the running window at the segment boundary, exactly
  the per-call merge of old;
* **tier ticks** (:class:`fps_tpu.tiering.MegastepTick`) — every
  ``check_every`` segments the merged window folds into a device-
  resident decayed count-min, the head re-ranks by (decayed count desc,
  id asc), and the replica / slot-map / gid arrays are re-derived from
  the canonical table — the host Retierer's boundary contract, traced;
* **overflow VOTE** — the gap PR 10 explicitly left: batches
  materialize inside the jit, so there is no host id stream to certify
  the compacted cold routes against. Before each segment runs, a cheap
  in-graph pre-pass re-reads the segment's raw id columns
  (``WorkerLogic.pulled_ids_traced``), counts every (step, worker)
  slice's cold ids against ``TableSpec.cold_budget`` exactly like the
  host certifier, and psums the verdict so every device agrees; the
  segment then ``lax.cond``-dispatches the compacted branch or the
  bit-identical static-route branch.

Collective cost stays O(traffic): the per-step collectives live inside
the scan body (one static occurrence however large K is) and the
boundary ticks move O(window) bytes per window — the contract auditor
pins the census as K-independent (``tools/audit_programs.py``
``mf_megastep`` rows).

Checkpoints land at megastep boundaries (the only host-visible
boundaries left); resume restores the snapshot and continues at
``start_megastep`` with the same per-(epoch, chunk) PRNG/shuffle
derivation, so a SIGKILL mid-megastep replays bit-identically (the
``megastep_kill`` chaos scenario).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fps_tpu.core import resilience
from fps_tpu.core.store import (
    device_slot_map,
    lookup_hot_slots,
    replica_from_shard,
    sketch_key,
    split_tiering,
)
from fps_tpu.obs.timing import PhaseTimer
from fps_tpu.parallel.mesh import (
    DATA_AXIS,
    SHARD_AXIS,
    key_to_replicated,
)

_log = logging.getLogger("fps_tpu.megastep")


def _psum_workers(x):
    return lax.psum(lax.psum(x, SHARD_AXIS), DATA_AXIS)


def vote_certifiable_tables(trainer, plan) -> frozenset:
    """Which compacted tables the device-side vote can certify: the
    logic's :meth:`~fps_tpu.core.api.WorkerLogic.pulled_ids_traced`
    stream (probed by abstract evaluation — no device work) must cover
    them. A compacted table the stream misses can never certify, so the
    megastep lowers the static routes for every table (mirrors the host
    certifier's "uncertifiable chunk reports every compacted table")."""
    compact = trainer._cold_compact_map()
    if not compact:
        return frozenset()
    cols = {
        k: jax.ShapeDtypeStruct((plan.local_batch,) + tuple(v.shape[1:]),
                                v.dtype)
        for k, v in plan.dataset.columns.items()
    }
    cols["weight"] = jax.ShapeDtypeStruct((plan.local_batch,), jnp.float32)

    def probe(batch):
        ids = trainer.logic.pulled_ids_traced(batch)
        return dict(ids) if ids is not None else {}

    try:
        covered = set(jax.eval_shape(probe, cols))
    except Exception:
        _log.warning("pulled_ids_traced probe failed; megastep cold "
                     "routes stay static", exc_info=True)
        return frozenset()
    if not set(compact) <= covered:
        return frozenset()
    return frozenset(compact)


def build_megastep_fn(trainer, plan, mode: str, K: int, tick=None):
    """One jitted program running K chunk segments of ``plan``.

    Signature of the returned callable::

        (tables, local_state, iargs, start_ci, key, tick_ops)
            -> (tables, local_state, outs, aux)

    ``start_ci`` is the epoch-relative index of the first chunk segment
    (the megastep's segments cover ``[start_ci, start_ci + K)``);
    ``key`` is the epoch key (``fold_in(run_key, epoch)``, replicated) —
    each segment folds its own chunk index in-graph, reproducing
    ``run_indexed``'s per-call key derivation bit-for-bit. ``outs``
    leaves carry ``K * steps_per_call`` leading rows; ``aux`` holds the
    per-segment overflow votes, the tier tick's updated decayed state /
    fold counter, and per-tick churn / re-rank telemetry (all
    replicated)."""
    from jax.sharding import PartitionSpec as P

    from fps_tpu.core.driver import worker_index
    from fps_tpu.core.store import fold_key, hot_key, ids_key, map_key

    T = trainer._indexed_call_steps(plan)
    s = trainer.config.sync_every
    tier = trainer._hot_tier_map()
    mapped = trainer._mapped_tables()
    track = trainer._track_specs()
    folds_on = trainer._hot_fold_map()
    E = trainer.config.hot_sync_every
    certifiable = vote_certifiable_tables(trainer, plan)
    compact = {
        name: C for name, C in sorted(trainer._cold_compact_map().items())
        if name in certifiable
    }
    if tick is not None:
        c_tick = tick.check_every
        if K % c_tick:
            # run_megastep validates this too; direct builders
            # (lowered_megastep_text) must fail the same way instead of
            # silently truncating the dispatch to fewer segments.
            raise ValueError(
                f"chunks_per_dispatch={K} must be a multiple of "
                f"tick.check_every={c_tick}")
        tick_tables = sorted(track)
        groups, c_seg = K // c_tick, c_tick
    else:
        tick_tables = []
        groups, c_seg = 1, K

    def mega_device(tables, local_state, iargs, start_ci, key, tick_ops):
        widx = worker_index()
        (tables, hot, maps, gids, sketches,
         fstates) = split_tiering(tables)

        def run_segment(carry, ci, compact_map):
            (tables, hot, maps, gids, sketches, fstates,
             local_state) = carry[:7]
            tick_rest = carry[7:]
            # run_indexed derives fold_in(fold_in(key, e), ci) on host;
            # fold_in is the same function traced, so the megastep's
            # in-graph derivation reproduces the stream bit-for-bit.
            ckey = jax.random.fold_in(key, ci)
            kk = jax.random.fold_in(ckey, widx)
            delta = trainer._init_hot_deltas(tables, tier)
            sk0 = {name: jnp.zeros_like(sketches[name])
                   for name in sorted(track)}

            def step_t(c, t, snapshot=None):
                (tables, hot, delta, fstates, sk, local_state, kk) = c
                kk, sub = jax.random.split(kk)
                batch = plan.local_batch_at(iargs, widx, t)
                (pushes, local_state, out, hp, hcounts,
                 sk) = trainer._compute_step(
                    tables, snapshot, local_state, batch, sub,
                    hot=hot, tier=tier, maps=maps, track=track, sk=sk,
                    compact=compact_map,
                )
                dropped = {}
                if tier:
                    tables, delta, dropped = trainer._apply_hot_split(
                        tables, delta, pushes, tier, hp, maps,
                        compact_map)
                else:
                    tables = trainer._apply_pushes(tables, pushes, hp)
                out = trainer._mount_hot_channel(out, hcounts, delta,
                                                 tier, dropped)
                out = jax.tree.map(_psum_workers, out)
                out = trainer._run_tap(out, tables, batch, local_state, t)
                return (tables, hot, delta, fstates, sk, local_state,
                        kk), out

            c0 = (tables, hot, delta, fstates, sk0, local_state, kk)
            start = ci * T
            if mode == "sync":
                if not tier:
                    c1, outs = lax.scan(
                        step_t, c0, start + jnp.arange(T, dtype=jnp.int32))
                else:
                    R, rem = divmod(T, E)
                    c1, outs = trainer._windowed_scan(
                        step_t, c0, tier,
                        head=(start + jnp.arange(R * E, dtype=jnp.int32)
                              .reshape(R, E)) if R else None,
                        tail=(start + R * E
                              + jnp.arange(rem, dtype=jnp.int32))
                        if rem else None,
                        gids=gids,
                    )
            else:
                def round_body(c, r):
                    snapshot = {
                        name: lax.all_gather(tb, SHARD_AXIS, tiled=True)
                        for name, tb in sorted(c[0].items())
                    }
                    c, outs = lax.scan(
                        lambda cc, t: step_t(cc, t, snapshot), c,
                        start + r * s + jnp.arange(s, dtype=jnp.int32),
                    )
                    return trainer._reconcile_carry(c, tier, gids), outs

                c1, outs = lax.scan(
                    round_body, c0, jnp.arange(T // s, dtype=jnp.int32))
                outs = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), outs)
            (tables, hot, delta, fstates, sk, local_state, _) = c1
            # Per-segment sketch merge — the exact end-of-call psum merge
            # the per-chunk driver does, so K segments accumulate the
            # identical window a K-dispatch host loop would.
            new_sketches = dict(sketches)
            if sk:
                with jax.named_scope("fps.sketch_merge"):
                    for name in sorted(sk):
                        new_sketches[name] = (sketches[name]
                                              + _psum_workers(sk[name]))
            return (tables, hot, maps, gids, new_sketches, fstates,
                    local_state) + tick_rest, outs

        def group_votes(maps, gci0):
            """Device-side uniform overflow votes for one tick group's
            segments ``[gci0, gci0 + c_seg)``: every (step, worker)
            slice of every compacted table must fit its ``cold_budget``
            lane — the host certifier's rule, counted in-graph from the
            raw id columns (padding positions count like real ids,
            exactly as the compaction sees them). Hot membership is
            constant within a group (ticks land only at group
            boundaries), so the whole group votes in one pre-pass and
            ONE ``(c_seg,)`` psum makes the verdicts uniform across
            devices — K scalar collectives would otherwise dominate the
            dispatch-overhead win this driver exists for."""

            def body(ok, t):
                batch = plan.local_batch_at(iargs, widx, t)
                ids = trainer.logic.pulled_ids_traced(batch)
                fit = jnp.bool_(True)
                for name in sorted(compact):
                    tids = ids[name].reshape(-1).astype(jnp.int32)
                    if name in mapped:
                        slot = lookup_hot_slots(maps[name], tids)
                        cold = (tids >= 0) & (slot < 0)
                    else:
                        cold = tids >= tier[name]
                    fit = fit & (jnp.sum(cold.astype(jnp.int32))
                                 <= compact[name])
                return ok, fit

            with jax.named_scope("fps.megastep_vote"):
                start = gci0 * T
                _, fits = lax.scan(
                    body, jnp.int32(0),
                    start + jnp.arange(c_seg * T, dtype=jnp.int32))
                seg_ok = jnp.all(fits.reshape(c_seg, T), axis=1)
                bad = _psum_workers((~seg_ok).astype(jnp.int32))
            return (bad == 0).astype(jnp.int32)

        def seg_step(carry, ci, vote):
            if not compact:
                carry, outs = run_segment(carry, ci, {})
                return carry, (outs, jnp.int32(1))

            def compacted(c):
                return run_segment(c, ci, compact)

            def static(c):
                c2, outs = run_segment(c, ci, {})
                # The compacted branch's out channel carries a
                # cold_dropped counter per compacted table (the device
                # observability net); pad the static branch to the same
                # structure so lax.cond's branches agree.
                ht = dict(outs[resilience.HOT_TIER_KEY])
                for name in sorted(compact):
                    entry = dict(ht[name])
                    entry["cold_dropped"] = jnp.zeros((T,), jnp.int32)
                    ht[name] = entry
                outs = dict(outs,
                            **{resilience.HOT_TIER_KEY: ht})
                return c2, outs

            carry, outs = lax.cond(vote > 0, compacted, static, carry)
            return carry, (outs, vote)

        def apply_tick(carry):
            """In-graph tier tick (``MegastepTick``): fold the merged
            window into the decayed count-min, re-rank the head by
            (decayed count desc, id asc), and re-derive replica /
            slot-map / gid arrays from the canonical table — valid at
            the boundary because every segment ended with a flush
            reconcile. Pure data flow: the program never recompiles on
            a re-rank, exactly like the host Retierer."""
            from fps_tpu import sketch as sklib
            from fps_tpu.tiering.tick import device_top_ids

            (tables, hot, maps, gids, sketches, fstates, local_state,
             dcm, tct) = carry
            hot, maps, gids = dict(hot), dict(maps), dict(gids)
            sketches, dcm = dict(sketches), dict(dcm)
            extras = {}
            with jax.named_scope("fps.megastep_tick"):
                for name in tick_tables:
                    spec = trainer.store.specs[name]
                    H = mapped[name]
                    st = sklib.dcm_fold_traced(
                        tick.spec, dcm[name], sketches[name], tct)
                    dcm[name] = st
                    sketches[name] = jnp.zeros_like(sketches[name])
                    est = sklib.cm_query(
                        tick._table_cm(name), st,
                        jnp.arange(spec.num_ids, dtype=jnp.int32))
                    cand = device_top_ids(est, H)
                    cur = lookup_hot_slots(maps[name], cand)
                    promoted = H - jnp.sum((cur >= 0).astype(jnp.int32))
                    churn = promoted.astype(jnp.float32) / H
                    # The host Retierer's rule exactly: re-rank only when
                    # churn exceeds the threshold AND something was
                    # actually promoted (an identical set must keep its
                    # slot order).
                    swap = (churn > tick.churn_threshold) & (promoted > 0)
                    sel = jnp.where(swap, cand, gids[name])
                    gids[name] = sel
                    maps[name] = device_slot_map(spec.num_ids, sel)
                    hot[name] = replica_from_shard(
                        tables[name], sel,
                        num_shards=trainer.num_shards)
                    extras[name] = {"churn": churn,
                                    "re_ranked": swap.astype(jnp.int32)}
            return (tables, hot, maps, gids, sketches, fstates,
                    local_state, dcm, tct + 1), extras

        carry0 = (tables, hot, maps, gids, sketches, fstates, local_state)
        if tick is not None:
            carry0 = carry0 + (dict(tick_ops["dcm"]),
                               jnp.asarray(tick_ops["tick"], jnp.int32))

        def group_body(carry, g):
            gci0 = start_ci + g * c_seg
            group_fit = (group_votes(carry[2], gci0) if compact
                         else jnp.ones((c_seg,), jnp.int32))

            def seg_at(c, j):
                return seg_step(c, gci0 + j, group_fit[j])

            carry, (outs, votes) = lax.scan(
                seg_at, carry, jnp.arange(c_seg, dtype=jnp.int32))
            extras = {}
            if tick is not None:
                carry, extras = apply_tick(carry)
            return carry, (outs, votes, extras)

        carry, (outs, votes, extras) = lax.scan(
            group_body, carry0, jnp.arange(groups, dtype=jnp.int32))
        # (groups, c_seg, T, ...) -> (K * T, ...)
        outs = jax.tree.map(
            lambda x: x.reshape((groups * c_seg * T,) + x.shape[3:]), outs)
        votes = votes.reshape(-1)
        (tables, hot, maps, gids, sketches, fstates,
         local_state) = carry[:7]
        aux = {"votes": votes, "tick": {}, "extras": extras}
        if tick is not None:
            aux["tick"] = {"dcm": carry[7], "tick": carry[8]}
        tables = {**tables,
                  **{hot_key(n): v for n, v in sorted(hot.items())},
                  **{map_key(n): v for n, v in sorted(maps.items())},
                  **{ids_key(n): v for n, v in sorted(gids.items())},
                  **{fold_key(n): v for n, v in sorted(fstates.items())},
                  **{sketch_key(n): v
                     for n, v in sorted(sketches.items())}}
        return tables, local_state, outs, aux

    table_specs = {name: P(SHARD_AXIS, None) for name in trainer.store.specs}
    table_specs.update({hot_key(name): P() for name in tier})
    table_specs.update({map_key(name): P() for name in sorted(mapped)})
    table_specs.update({ids_key(name): P() for name in sorted(mapped)})
    table_specs.update({sketch_key(name): P() for name in sorted(track)})
    table_specs.update({fold_key(name): P(SHARD_AXIS, None)
                        for name in sorted(folds_on)})
    ls_spec = P((DATA_AXIS, SHARD_AXIS))

    def run(tables, local_state, iargs, start_ci, key, tick_ops):
        shmapped = jax.shard_map(
            mega_device,
            mesh=trainer.mesh,
            in_specs=(
                table_specs,
                jax.tree.map(lambda _: ls_spec, local_state),
                jax.tree.map(lambda _: P(), iargs),
                P(),
                P(),
                jax.tree.map(lambda _: P(), tick_ops),
            ),
            out_specs=(
                table_specs,
                jax.tree.map(lambda _: ls_spec, local_state),
                P(),
                P(),
            ),
            check_vma=False,
        )
        return shmapped(tables, local_state, iargs, start_ci, key,
                        tick_ops)

    donate = (0, 1) if trainer.config.donate else ()
    return jax.jit(run, donate_argnums=donate)


def run_megastep(trainer, tables, local_state, plan, key, *,
                 epochs: int = 1, chunks_per_dispatch: int = 4,
                 on_megastep=None, checkpointer=None,
                 checkpoint_every: int = 0, start_megastep: int = 0,
                 as_numpy: bool = True, rollback=None, recorder=None,
                 health=None, watchdog=None, tick=None):
    """Drive ``epochs`` passes of ``plan`` in K-chunk megasteps.

    Each dispatch runs ``chunks_per_dispatch`` chunk segments of
    ``trainer._indexed_call_steps(plan)`` steps each — bit-identical to
    the same number of per-chunk ``run_indexed`` dispatches (tables,
    metrics, and checkpoints; tested), but with per-chunk Python
    dispatch, host sync, and transfer overhead out of the hot loop.

    ``chunks_per_dispatch="auto"`` replaces the flag with measurement:
    a short calibration window (:mod:`fps_tpu.core.autok`) times one-
    and two-cadence-block dispatches on throwaway copies, models the
    host-serial share as ``h / (h + K*c)``, and picks the smallest K
    that clears the target share — rounded to the tick cadence, capped
    at one epoch's calls. The chosen K (``megastep.auto_k`` gauge) then
    drives a run bit-identical to passing it explicitly. Resuming a
    run (``start_megastep > 0``) should pass the original chosen K
    explicitly — megastep indices are counted in units of K.

    Checkpoints land every ``checkpoint_every`` megasteps under the
    GLOBAL megastep index (``start_megastep`` resumes there — shuffles
    and PRNG keys derive from the (epoch, chunk) pair, so a restart
    replays bit-identically). ``rollback`` / ``health`` / ``watchdog``
    adjudicate at megastep granularity: a poisoned megastep restores
    the pre-dispatch state and quarantines its index (the per-segment
    attribution rides the quarantine event via
    :func:`fps_tpu.core.resilience.health_by_segment`).

    ``tick`` (a :class:`fps_tpu.tiering.MegastepTick`) runs the
    adaptive-tiering boundary in-graph every ``tick.check_every``
    segments; ``chunks_per_dispatch`` must be a multiple of that
    cadence. The decayed sketch state round-trips between dispatches as
    device arrays (no forced host sync); host mirrors update lazily at
    checkpoint boundaries and end of run.

    Returns ``(tables, local_state, per-megastep metrics list)`` with
    each entry trimmed to the epoch's real steps (phantom weight-0
    trailing segments dropped, like ``run_indexed``).
    """
    from fps_tpu.core.driver import (
        _beat,
        _find_heartbeat,
        _phase,
        _watch,
    )

    cfg = trainer.config
    auto_k = isinstance(chunks_per_dispatch, str)
    if auto_k:
        if chunks_per_dispatch != "auto":
            raise ValueError(
                f"chunks_per_dispatch must be an int >= 1 or 'auto', "
                f"got {chunks_per_dispatch!r}")
        K = None  # resolved by the calibration window below
    else:
        K = int(chunks_per_dispatch)
        if K < 1:
            raise ValueError(
                f"chunks_per_dispatch must be >= 1, got "
                f"{chunks_per_dispatch}")
    if cfg.push_delay:
        raise ValueError(
            "run_megastep does not support push_delay: the in-flight ring "
            "buffer would need a per-segment flush that reorders delivery "
            "against the in-graph boundary ticks — use fit_stream / "
            "run_indexed for delayed-push emulation")
    if cfg.auto_tier:
        raise ValueError(
            "run_megastep does not support auto_tier: the planner's "
            "mid-run recompile has no boundary to land on inside one "
            "compiled program — plan first (tools/plan.py), then attach "
            "a MegastepTick for in-graph re-ranking")
    trainer._check_rollback(rollback)
    trainer._check_health(health)
    mode = "sync" if cfg.sync_every is None else "ssp"
    if (cfg.sync_every or None) != (plan.sync_every or None):
        raise ValueError("plan.sync_every must match TrainerConfig")
    if tick is not None:
        from fps_tpu.tiering.tick import MegastepTick

        if not isinstance(tick, MegastepTick):
            raise TypeError(
                f"tick must be a fps_tpu.tiering.MegastepTick, got "
                f"{type(tick).__name__}")
        if trainer.retierer is not None and trainer.retierer is not tick:
            raise ValueError(
                "trainer already has a retierer attached — run_megastep "
                "drives tier boundaries in-graph via its own MegastepTick")
        if not auto_k and K % tick.check_every:
            raise ValueError(
                f"chunks_per_dispatch={K} must be a multiple of "
                f"tick.check_every={tick.check_every} so every tick "
                "lands on a static in-graph boundary")
        # Attach-then-validate, restoring on failure: a rejected call
        # must not leave the tick installed as the trainer's retierer
        # (the mapped-tier resolution needs it attached to be checked
        # at all, so the attach cannot simply move below the check).
        prev_retierer = trainer.retierer
        trainer.retierer = tick
        if not trainer._mapped_tables():
            trainer.retierer = prev_retierer
            raise ValueError(
                "MegastepTick attached but no table resolves onto the "
                "mapped tier (needs a partial hot_tier, hot_sync_every "
                "> 1, and a multi-device mesh)")
    elif trainer.retierer is not None:
        raise ValueError(
            "run_megastep runs tier boundaries in-graph: attach a "
            "fps_tpu.tiering.MegastepTick (tick=...), not a host "
            "Retierer")
    rec = recorder if recorder is not None else trainer.recorder
    timer = PhaseTimer(rec) if rec is not None else None
    hb = _find_heartbeat(rec)
    quarantine = (rollback if rollback is not None and
                  resilience.as_guard(cfg.guard) is not None else None)
    sync_each = (quarantine is not None or health is not None
                 or watchdog is not None)
    from fps_tpu.core.driver import calls_per_epoch_of

    T_call = trainer._indexed_call_steps(plan)
    n_calls = calls_per_epoch_of(plan, T_call)
    T = plan.steps_per_epoch
    tables = trainer._attach_hot(tables, timer)
    if auto_k:
        from fps_tpu.core.autok import calibrate_chunks_per_dispatch

        K, overhead_s, per_chunk_s = calibrate_chunks_per_dispatch(
            trainer, tables, local_state, plan, key, mode=mode,
            tick=tick, n_calls=n_calls)
        if rec is not None:
            rec.set("megastep.auto_k", K)
            rec.event("megastep_auto_k", chosen_k=K,
                      overhead_s=round(overhead_s, 6),
                      per_chunk_s=round(per_chunk_s, 6))
    M = -(-n_calls // K)
    compact_cfg = trainer._cold_compact_map()
    vote_on = bool(compact_cfg) and bool(
        vote_certifiable_tables(trainer, plan))
    fn = trainer._get_megastep_fn(plan, mode, K, tick)
    if rec is not None:
        rec.set("megastep.chunks_per_dispatch", K)
    all_metrics = []
    deferred_votes = []  # device vote arrays, converted lazily
    deferred_ticks = []  # device per-tick churn/re-rank telemetry
    saved_at = None
    tick_dev = None  # device-resident {dcm, tick} round-tripping dispatches
    iargs, cur_epoch = None, None
    end = epochs * M

    def tick_host_sync(tables):
        """Lazy host-mirror sync (+ sidecar) for the in-graph tick: only
        checkpoint boundaries and end-of-run pay the device→host read."""
        if tick is None or tick_dev is None:
            return
        tick.absorb(trainer, tick_dev, tables)

    def fold_votes(rec):
        if rec is None or not compact_cfg:
            return
        for votes, real in deferred_votes:
            # Weight the fold by REAL segments: a trimmed final
            # dispatch still runs K in-graph segments, but its trailing
            # weight-0 phantoms did no work — counting them would make
            # megastep.windows (and the vote counters) disagree with
            # the dispatched-work totals the bench asserts on. Phantom
            # segments are always the trailing ones, so the first
            # ``real`` votes are exactly the real windows' verdicts.
            if votes is None:
                # Uncertifiable dispatch: every real segment fell back
                # to the static routes. The verdict is ONE AND-ed bit
                # per window over every compacted table — per-table
                # attribution would multiply-count it, so the counter
                # is unlabeled.
                rec.inc("cold_route.vote_overflow_windows", real)
                continue
            v = np.asarray(votes).reshape(-1)[:real]
            ok = int((v != 0).sum())
            if ok:
                rec.inc("cold_route.vote_compact_windows", ok)
            if ok < v.size:
                rec.inc("cold_route.vote_overflow_windows",
                        int(v.size) - ok)
        deferred_votes.clear()

    def fold_ticks(rec):
        if rec is None:
            return
        for extras in deferred_ticks:
            for t in sorted(extras):
                rr = np.asarray(extras[t]["re_ranked"]).reshape(-1)
                ch = np.asarray(extras[t]["churn"]).reshape(-1)
                if int(rr.sum()):
                    rec.inc("tiering.re_ranks", int(rr.sum()), table=t)
                if ch.size:
                    rec.set("tiering.churn", float(ch[-1]), table=t)
        deferred_ticks.clear()

    try:
        for g in range(start_megastep, end):
            e, m = divmod(g, M)
            if rollback is not None and g in rollback.preset:
                rollback.skip(g)
                if rec is not None:
                    rec.inc("rollback.preset_skipped")
                    rec.flush()
                continue
            if cur_epoch != e:
                with _phase(timer, "ingest"):
                    iargs = plan.epoch_args(e)
                cur_epoch = e
            ekey = key_to_replicated(jax.random.fold_in(key, e),
                                     trainer.mesh)
            if quarantine is not None:
                last_good = (resilience.tree_copy(tables),
                             resilience.tree_copy(local_state))
                tick_good = (resilience.tree_copy(tick_dev)
                             if tick_dev is not None else None)
            if tick is not None:
                tick_ops = (tick_dev if tick_dev is not None
                            else tick.tick_ops(trainer))
            else:
                tick_ops = {}
            _beat(hb, g, "dispatch")
            restored = None
            with _watch(watchdog, "megastep", g):
                with _phase(timer, "megastep"):
                    tables, local_state, metrics, aux = fn(
                        tables, local_state, iargs, np.int32(m * K),
                        ekey, tick_ops)
                # Trim phantom weight-0 trailing rows so the epoch's
                # concatenated metrics match run_indexed's exactly.
                keep = max(0, min(K * T_call, T - m * K * T_call))
                # Real (non-phantom) chunk segments of this dispatch —
                # the unit megastep.windows and the vote fold count in.
                real_segs = min(K, -(-keep // T_call)) if T_call else K
                if keep < K * T_call:
                    metrics = jax.tree.map(lambda x: x[:keep], metrics)
                if quarantine is not None:
                    with _phase(timer, "host_sync"):
                        metrics, restored = trainer._maybe_quarantine(
                            quarantine, last_good, metrics, g, "megastep")
                elif sync_each:
                    with _phase(timer, "host_sync"):
                        metrics = jax.tree.map(np.asarray, metrics)
            if tick is not None:
                tick_dev = dict(aux["tick"])
            if compact_cfg:
                # Votes count at dispatch time even for a later-
                # quarantined megastep — the same convention as the host
                # certifier's cold_route.compact_chunks, which run_chunk
                # increments before adjudication. Each entry carries the
                # dispatch's REAL segment count so the fold can drop
                # trailing phantom windows.
                deferred_votes.append(
                    (aux["votes"] if vote_on else None, real_segs))
            ev = {"index": g} if rec is not None else None
            poison = 0
            if sync_each and (rec is not None or health is not None):
                poison = trainer._fold_metrics_accounting(rec, metrics, ev)
            if rec is not None:
                rec.inc("megastep.windows", real_segs)
                if restored is not None:
                    rec.inc("rollback.quarantined")
                    ev["quarantined"] = True
                    # Per-segment attribution: which in-graph chunk first
                    # reported poison (global chunk index within epoch).
                    seg = resilience.health_by_segment(metrics, K, T_call)
                    bad = [m * K + i for i, p in enumerate(seg) if p]
                    ev["poisoned_chunks_in_graph"] = bad
            trainer._apply_health_decision(health, rec, g, poison,
                                           "megastep")
            if restored is not None:
                if rec is not None:
                    rec.event("megastep", phases=timer.chunk_summary(),
                              **ev)
                    rec.flush()
                tables, local_state = restored
                if tick is not None:
                    tick_dev = tick_good
                continue
            if tick is not None and aux["extras"]:
                # Tick telemetry only for SURVIVING dispatches: a
                # quarantined megastep's re-ranks rolled back with its
                # state (the host path never counts re-ranks for
                # quarantined chunks either).
                deferred_ticks.append(aux["extras"])
            all_metrics.append(metrics)
            trainer.store.tables = dict(tables)
            if on_megastep is not None:
                with _phase(timer, "host_sync"):
                    host = jax.tree.map(np.asarray, metrics)
                if rec is not None and not sync_each:
                    trainer._fold_metrics_accounting(rec, host, ev)
                all_metrics[-1] = host
                with _phase(timer, "callback"):
                    on_megastep(g, host)
            if (checkpointer is not None and checkpoint_every > 0
                    and (g + 1) % checkpoint_every == 0):
                with _phase(timer, "checkpoint"):
                    tick_host_sync(tables)
                    trainer._save_checkpoint(checkpointer, g + 1,
                                             local_state)
                    if tick is not None and tick.state_dir is not None:
                        tick.save_boundary(g + 1, tables)
                saved_at = g + 1
            if rec is not None:
                rec.event("megastep", phases=timer.chunk_summary(), **ev)
                rec.flush()
        trainer.store.tables = dict(tables)
        tick_host_sync(tables)
        if (checkpointer is not None and end > start_megastep
                and saved_at != end):
            with _phase(timer, "checkpoint"):
                trainer._save_checkpoint(checkpointer, end, local_state,
                                         final=True)
                if tick is not None and tick.state_dir is not None:
                    tick.save_boundary(end, tables)
    finally:
        fold_votes(rec)
        fold_ticks(rec)
        if checkpointer is not None:
            with _phase(timer, "checkpoint"):
                checkpointer.flush()
    if on_megastep is None and as_numpy:
        with _phase(timer, "host_sync"):
            all_metrics = [jax.tree.map(np.asarray, mtree)
                           for mtree in all_metrics]
        if rec is not None and not sync_each:
            for mtree in all_metrics:
                trainer._fold_metrics_accounting(rec, mtree)
    if rec is not None:
        rec.flush()
    return tables, local_state, all_metrics
