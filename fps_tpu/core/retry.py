"""Bounded retry/backoff + the storage fault-injection seam.

Everything coordination-critical in this framework — snapshot chains,
the LSM compactor, the pod lease/fence, tiering sidecars, the fleet
step fence — lives on ONE shared filesystem. The chaos model used to
kill processes and tear files; a *misbehaving* filesystem (ENOSPC, EIO,
slow writes, stale NFS-style reads, vanishing dirents) needs two more
pieces, both here:

* :class:`RetryPolicy` — errno classification (retryable vs fatal),
  bounded deterministic exponential backoff with the PR-11 seeded
  jitter, and a deadline cap; :func:`call_with_retry` drives it. The
  write planes (checkpoint publishes, compaction, sidecars) retry
  transient errors and then DEGRADE (skip the publish, burn a
  staleness budget) instead of crashing training; the read planes
  (watcher/fleet polls) degrade immediately to last-good state.
* the **fault seam** — :func:`fault_check` is called by every
  framework file-operation site (``_atomic_savez``, snapshot reads,
  lease/fence writes, sidecar writes, directory scans) with an
  ``(op, path)`` pair. An installed injector
  (:mod:`fps_tpu.testing.faultfs`) classifies the path
  (:func:`classify_path`) and may raise an ``OSError``, sleep
  (latency), or return a directive the seam honors (``"torn"`` for a
  torn rename, ``("redirect", shadow)`` for a stale
  read-after-rename). With no injector installed the seam is one
  attribute read — zero cost in production.

Stdlib-only by contract: the pod coordinator (``fps_tpu/supervise/
pod.py``, loaded by file path on jax-free login nodes) and the serving
plane (stub-root import, no jax) both use this module.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import hashlib
import os
import re
import time

__all__ = [
    "RETRYABLE_ERRNOS", "FATAL_ERRNOS", "classify_error",
    "RetryPolicy", "call_with_retry", "DEFAULT_PUBLISH_RETRY",
    "classify_path", "fault_check", "read_path", "install_injector",
    "remove_injector", "get_injector", "FAULTFS_ENV",
    "RETRYABLE_NET_ERRNOS", "classify_net", "DEFAULT_NET_RETRY",
    "net_fault_check", "install_net_injector", "remove_net_injector",
    "get_net_injector", "FAULTNET_ENV",
]

# ---------------------------------------------------------------------------
# Errno classification.
# ---------------------------------------------------------------------------

# Transient-environment errnos: the operation may succeed if simply
# retried (disk pressure clears, the NFS server answers, the dirent
# becomes visible). ENOENT is retryable by design — on a hostile shared
# filesystem a just-renamed file can be transiently invisible to a
# sibling host; callers for whom a missing file is a REAL terminal
# condition (a pinned-but-gc'd checkpoint) do not route through
# call_with_retry at all.
RETRYABLE_ERRNOS = frozenset({
    _errno.ENOSPC, _errno.EIO, _errno.ETIMEDOUT, _errno.EAGAIN,
    _errno.ENOENT, _errno.ESTALE, _errno.EINTR, _errno.EBUSY,
    _errno.EDQUOT,
})

# Permanent-environment errnos: retrying cannot help (a read-only or
# mispermissioned mount needs an operator, not a backoff loop) — these
# must surface immediately and loudly.
FATAL_ERRNOS = frozenset({
    _errno.EACCES, _errno.EROFS, _errno.EPERM, _errno.ENOTDIR,
    _errno.EISDIR, _errno.ENAMETOOLONG, _errno.ENODEV, _errno.ENXIO,
})


def classify_error(err: BaseException) -> str:
    """``"retryable"`` or ``"fatal"`` for one exception. Only OSErrors
    with a known-transient errno are retryable; everything else —
    fatal errnos, unknown errnos, and non-OSError exceptions (a pod
    fence refusal, a corruption error) — is fatal: retrying an error we
    do not understand hides bugs behind latency."""
    if isinstance(err, OSError) and err.errno in RETRYABLE_ERRNOS:
        return "retryable"
    return "fatal"


# The wire plane's transient errnos: a refused/reset/unreachable peer
# or a timed-out socket may answer on the next attempt (the server is
# restarting, a partition is healing, a kernel buffer drained). These
# are DISJOINT in spirit from the filesystem set above — a serve client
# must never treat EACCES-on-connect as transient.
RETRYABLE_NET_ERRNOS = frozenset({
    _errno.ECONNREFUSED, _errno.ECONNRESET, _errno.ECONNABORTED,
    _errno.EPIPE, _errno.ETIMEDOUT, _errno.EAGAIN, _errno.EINTR,
    _errno.EHOSTUNREACH, _errno.ENETUNREACH, _errno.ENETDOWN,
    _errno.ENETRESET, _errno.EADDRNOTAVAIL,
})


def classify_net(err: BaseException) -> str:
    """The wire twin of :func:`classify_error`: ``"retryable"`` or
    ``"fatal"`` for one network exception. Retryable: socket timeouts
    (``TimeoutError`` covers ``socket.timeout`` since 3.10), the
    connection-lifecycle OSError subclasses (refused / reset / aborted /
    broken pipe — the peer may be mid-restart), and OSErrors carrying a
    transient network errno. ``EOFError``/``ConnectionError`` raised by
    a framing layer on a half-closed peer is retryable for the same
    reason: reconnect-and-resend (with idempotent request ids) is the
    correct response. Everything else — protocol violations, CRC
    failures, application errors — is fatal: retrying a malformed
    conversation hides bugs behind latency."""
    if isinstance(err, TimeoutError):
        return "retryable"
    if isinstance(err, (ConnectionError, EOFError)):
        # ConnectionRefusedError/ConnectionResetError/BrokenPipeError/
        # ConnectionAbortedError plus the bare ConnectionError a client
        # raises on an empty read (peer closed mid-conversation).
        return "retryable"
    if isinstance(err, OSError) and err.errno in RETRYABLE_NET_ERRNOS:
        return "retryable"
    return "fatal"


# ---------------------------------------------------------------------------
# Bounded deterministic retry.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic seeded jitter.

    ``retries`` transient failures are retried (``retries + 1`` attempts
    total); backoff for attempt ``i`` is ``base_s * factor**i`` capped at
    ``max_backoff_s``, stretched by up to ``jitter`` fraction via the
    PR-11 sha256 scheme — seeded by ``seed`` so a given process retries
    on a REPLAYABLE schedule while distinct seeds (per host/plane)
    desynchronize, instead of stampeding the shared filesystem in
    lockstep. ``deadline_s`` caps total time inside one
    :func:`call_with_retry` (attempts + sleeps): a slow-but-failing
    filesystem must not hold a boundary hostage for minutes."""

    retries: int = 3
    base_s: float = 0.02
    factor: float = 2.0
    max_backoff_s: float = 1.0
    jitter: float = 0.25
    deadline_s: float | None = 20.0
    seed: str = ""

    def __post_init__(self):
        if self.retries < 0:
            raise ValueError(f"retries must be >= 0, got {self.retries}")
        if self.base_s < 0 or self.factor < 1.0:
            raise ValueError("base_s must be >= 0 and factor >= 1.0")
        if not 0.0 <= self.jitter:
            raise ValueError(f"jitter must be >= 0, got {self.jitter}")

    def backoff_s(self, attempt: int) -> float:
        """Deterministic backoff before retry ``attempt`` (0-based)."""
        base = min(self.base_s * self.factor ** attempt,
                   self.max_backoff_s)
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        h = hashlib.sha256(
            f"{self.seed}:{attempt}".encode()).digest()
        frac = int.from_bytes(h[:8], "big") / float(1 << 64)
        return base * (1.0 + self.jitter * frac)


# The write-plane default: worst case ~0.14s of backoff — negligible
# beside a real serialize+fsync, generous against one transient blip.
DEFAULT_PUBLISH_RETRY = RetryPolicy(retries=3, base_s=0.02,
                                    max_backoff_s=0.25, deadline_s=10.0)

# The wire-plane default: more retries than the write plane (a serve
# request is cheap to resend and the request-id dedupe makes resends
# idempotent) but a tighter per-call deadline — a query client must
# degrade to "serving unavailable" in seconds, not hold a bench or a
# reader hostage for the filesystem plane's 10s.
DEFAULT_NET_RETRY = RetryPolicy(retries=5, base_s=0.02,
                                max_backoff_s=0.5, deadline_s=5.0)


def call_with_retry(fn, *, policy: RetryPolicy, op: str = "",
                    on_retry=None, classify=classify_error,
                    clock=time.monotonic, sleep=time.sleep):
    """Run ``fn()`` under ``policy``: transient failures retry with
    backoff until the retry budget or the deadline is exhausted, then
    the LAST error re-raises unchanged (the caller's degrade logic sees
    the real errno). Fatal errors re-raise immediately. ``on_retry``
    (optional ``fn(attempt, err, delay_s)``) is the telemetry hook."""
    t0 = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if classify(e) != "retryable" or attempt >= policy.retries:
                raise
            delay = policy.backoff_s(attempt)
            if (policy.deadline_s is not None
                    and clock() - t0 + delay > policy.deadline_s):
                raise
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
            attempt += 1


# ---------------------------------------------------------------------------
# Path classification (which plane an operation belongs to).
# ---------------------------------------------------------------------------

# Ordered: first match wins. Classes are the fault injector's targeting
# unit — a schedule against "lease" can never hit a snapshot publish.
_PATH_CLASSES = (
    ("lease", re.compile(r"pod_lease\.json")),
    ("fence", re.compile(
        r"pod_fence\.json|serve_fence\.json|ready_.*\.json")),
    ("sidecar", re.compile(r"tiering-\d+\.npz(\.tmp\.npz)?")),
    ("control", re.compile(
        r"pod_control\.json|pod_state\.json|supervisor_state\.json")),
    ("journal", re.compile(r"(journal|events)-.*\.jsonl")),
    ("liveness", re.compile(r"heartbeat_.*\.json")),
    ("snapshot", re.compile(
        r"ckpt_\d+\.npz|delta_\d+_\d+\.npz|.*\.tmp\.npz|.*\.corrupt")),
)


def classify_path(path: str) -> str:
    """The storage plane ``path`` belongs to: ``lease`` / ``fence`` /
    ``sidecar`` / ``control`` / ``journal`` / ``liveness`` /
    ``snapshot`` / ``other``. Matches on the basename only —
    directories never change a file's plane."""
    name = os.path.basename(path.rstrip("/\\"))
    for cls, pat in _PATH_CLASSES:
        if pat.fullmatch(name):
            return cls
    if os.path.splitext(name)[1] == "":
        # A bare directory operand (listdir seams) classifies by any
        # plane-marker file it could hold — callers pass the dir of
        # snapshots, so default the extension-free case to snapshot.
        return "snapshot"
    return "other"


# ---------------------------------------------------------------------------
# The fault seam.
# ---------------------------------------------------------------------------

FAULTFS_ENV = "FPS_TPU_FAULTFS"

_injector = None
_env_checked = False


def install_injector(inj) -> None:
    """Install ``inj`` as the process-global fault injector. The
    injector's ``check(op, path_class, path)`` is called by every seam;
    see :mod:`fps_tpu.testing.faultfs` for the reference implementation.
    Passing None uninstalls."""
    global _injector
    _injector = inj


def remove_injector() -> None:
    install_injector(None)


def get_injector():
    """The installed injector, activating the :data:`FAULTFS_ENV`
    contract lazily on first call: a subprocess launched with
    ``FPS_TPU_FAULTFS=<json-or-path>`` self-installs the described
    schedule (the chaos scenarios' cross-process hook) without any
    caller wiring. Returns None when no injector is configured."""
    global _env_checked, _injector
    if _injector is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get(FAULTFS_ENV)
        if spec:
            _injector = _load_env_injector(spec)
    return _injector


def _load_env_injector(spec: str):
    """Build a FaultFS from the env spec — faultfs.py loaded by FILE
    path (it is stdlib-only, like this module), so env activation works
    in jax-free agents and stub-root serving processes alike."""
    import importlib.util as _ilu
    import sys as _sys

    mod = _sys.modules.get("fps_tpu.testing.faultfs")
    if mod is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "testing", "faultfs.py")
        ld = _ilu.spec_from_file_location("_fps_faultfs", path)
        mod = _ilu.module_from_spec(ld)
        _sys.modules[ld.name] = mod
        ld.loader.exec_module(mod)
    return mod.FaultFS.from_spec(spec)


def read_path(path: str) -> str:
    """The read seam in path form: run :func:`fault_check` for a read
    of ``path`` and return the EFFECTIVE path — the injector's
    pre-rename shadow under a ``("redirect", shadow)`` directive (the
    stale NFS read), else ``path`` unchanged. One shared helper so the
    checkpoint, snapshot-format, and fleet read sites cannot drift."""
    directive = fault_check("read", path)
    if isinstance(directive, tuple) and directive[0] == "redirect":
        return directive[1]
    return path


def fault_check(op: str, path: str, *, path_class: str | None = None):
    """The seam: called immediately before a framework file operation.
    ``op`` is one of ``write`` / ``fsync`` / ``replace`` / ``read`` /
    ``listdir`` / ``remove``. With no injector installed this is one
    module-attribute read. An injector may raise an ``OSError``
    (injected errno), sleep (injected latency), or return a directive:
    ``"torn"`` (rename seams publish a truncated file and fail) or
    ``("redirect", shadow_path)`` (read seams read pre-rename content —
    the stale NFS read). Seams that get a directive they do not
    implement ignore it."""
    inj = _injector if _injector is not None else get_injector()
    if inj is None:
        return None
    return inj.check(op, path_class or classify_path(path), path)


# ---------------------------------------------------------------------------
# The network fault seam (the wire twin of the above).
# ---------------------------------------------------------------------------

FAULTNET_ENV = "FPS_TPU_FAULTNET"

_net_injector = None
_net_env_checked = False


def install_net_injector(inj) -> None:
    """Install ``inj`` as the process-global NETWORK fault injector.
    Its ``check(op, peer_class)`` is consulted by every socket seam in
    :mod:`fps_tpu.serve.wire` / :mod:`fps_tpu.serve.net`; see
    :mod:`fps_tpu.testing.faultnet` for the reference implementation.
    Passing None uninstalls."""
    global _net_injector
    _net_injector = inj


def remove_net_injector() -> None:
    install_net_injector(None)


def get_net_injector():
    """The installed network injector, activating the
    :data:`FAULTNET_ENV` contract lazily on first call — a subprocess
    launched with ``FPS_TPU_FAULTNET=<json-or-path>`` self-installs the
    described schedule, exactly like the faultfs env hook. Returns None
    when no injector is configured."""
    global _net_env_checked, _net_injector
    if _net_injector is None and not _net_env_checked:
        _net_env_checked = True
        spec = os.environ.get(FAULTNET_ENV)
        if spec:
            _net_injector = _load_env_net_injector(spec)
    return _net_injector


def _load_env_net_injector(spec: str):
    """Build a FaultNet from the env spec — faultnet.py loaded by FILE
    path (stdlib-only, like this module), so env activation works in
    jax-free agents and stub-root serving processes alike."""
    import importlib.util as _ilu
    import sys as _sys

    mod = _sys.modules.get("fps_tpu.testing.faultnet")
    if mod is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "testing", "faultnet.py")
        ld = _ilu.spec_from_file_location("_fps_faultnet", path)
        mod = _ilu.module_from_spec(ld)
        _sys.modules[ld.name] = mod
        ld.loader.exec_module(mod)
    return mod.FaultNet.from_spec(spec)


def net_fault_check(op: str, peer_class: str):
    """The wire seam: called immediately before a framework socket
    operation. ``op`` is one of ``connect`` / ``accept`` / ``send`` /
    ``recv``; ``peer_class`` names which conversation the socket
    belongs to (``"serve"`` for query traffic, ``"fleet"`` for
    reader-side sockets — the injector's targeting unit, like
    faultfs's path classes). With no injector installed this is one
    module-attribute read. An injector may raise (connect-refused,
    reset), sleep (read/write delay), or return a directive the seam
    honors: ``("cut", nbytes)`` — send only a prefix then drop the
    connection, the torn-frame producer; ``("trickle", chunk, delay_s)``
    — slow-peer byte-trickle; ``"drop"`` — accept seams close the
    connection unserved (one-way partition). Seams that get a directive
    they do not implement ignore it."""
    inj = (_net_injector if _net_injector is not None
           else get_net_injector())
    if inj is None:
        return None
    return inj.check(op, peer_class)
