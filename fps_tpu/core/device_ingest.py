"""Device-resident ingest: the zero-host-traffic fast path for epoch training.

The host ingest iterator (:mod:`fps_tpu.core.ingest`) regenerates and
re-uploads every chunk — the right shape for genuinely unbounded streams
(the reference's ``DataStream`` model), but wasteful for multi-epoch
benchmark training: on a TPU VM the host→device link is orders of magnitude
slower than HBM, and shuffling 20M ratings in numpy costs seconds per epoch.

Here the columnar dataset is uploaded **once** and batches are built by
on-device gathers:

* **routing** — the reference partitions the stream so worker-local state
  stays local (e.g. MF keyed by user; SURVEY.md §3.3). The per-worker
  queues (example indices with ``route_key % num_workers == w``) are
  computed on host *once* at construction and uploaded as a padded
  ``(num_workers, max_queue)`` matrix;
* **shuffle** — per epoch, each worker's queue is traversed under a
  permutation of ``[0, count)``: ``shuffle="sort"`` draws a true uniform
  permutation (on-device argsort of random keys), ``shuffle="interleave"``
  (default) walks a per-epoch randomized block transpose — view positions
  as an ``(r, c)`` grid and emit transposed with a cyclic offset,
  ``pos -> ((pos % r) * c + pos // r + off) mod r*c`` — an exact bijection
  in pure int32 arithmetic (no sort, no host traffic; consecutive batch
  entries sit ``c`` apart in stream order, a fresh stride every epoch).
  The reference itself never shuffles (it trains in stream arrival
  order), so any epoch permutation is already an upgrade; ``shuffle=None``
  preserves stream order exactly like the reference;
* **padding** — workers with short queues (skewed routing) read zero-weight
  padding rows, identical semantics to the host path.

Two consumption styles, one geometry (:class:`DeviceEpochPlan`):

* :func:`device_epoch_chunks` materializes ``(T, B)`` chunks on device for
  the generic chunked driver (``Trainer.fit_stream``);
* ``Trainer.run_indexed`` traces :meth:`DeviceEpochPlan.local_batch_at`
  *inside* its compiled scan, fusing ingest into the training program —
  one dispatch per epoch, zero per-epoch host↔device traffic.

All grid geometry is baked into the trace as constants: integer div/mod by
*traced* divisors makes XLA:TPU compiles pathologically slow (40s+ observed
for this very function), and the grid row count is a power of two so the
remaining div/mod lower to shifts/masks.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS, host_to_replicated

Array = jax.Array

WORKER_AXES = (DATA_AXIS, SHARD_AXIS)

# Cap on interleave grid rows: consecutive emitted examples sit ~count/r
# apart in stream order, and r*c must stay int32-safe.
_GRID_ROWS_MAX = 1 << 12


class DeviceDataset:
    """A columnar dataset resident on every device of the mesh.

    Columns are equal-length arrays, replicated across the mesh (``P()``)
    so any worker can gather any row. Per-(route_key, num_workers) queue
    partitions are computed once on host and cached on device.
    """

    def __init__(self, mesh, data: Mapping[str, np.ndarray]):
        self.mesh = mesh
        lengths = {k: len(v) for k, v in data.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"column lengths differ: {lengths}")
        self.n = next(iter(lengths.values()))
        self._host_data = {k: np.asarray(v) for k, v in data.items()}
        self.columns = {
            k: host_to_replicated(v, mesh) for k, v in self._host_data.items()
        }
        self._queues: dict[tuple[str | None, int], tuple[Array, np.ndarray]] = {}

    def queues(self, route_key: str | None, num_workers: int):
        """(device queue matrix, host per-worker counts).

        The queue matrix is ``(num_workers, max_queue)`` int32 — worker
        ``w``'s first ``counts[w]`` entries are the example indices it owns,
        in stream order; the rest is padding (clamped reads, weight 0).
        """
        ck = (route_key, num_workers)
        if ck not in self._queues:
            if route_key is None:
                counts = np.full(num_workers, self.n // num_workers, np.int64)
                counts[: self.n % num_workers] += 1
                maxq = max(int(counts.max()), 1)
                q = np.zeros((num_workers, maxq), np.int32)
                for w in range(num_workers):
                    q[w, : counts[w]] = np.arange(w, self.n, num_workers)
            else:
                keys = self._host_data[route_key].astype(np.int64) % num_workers
                order = np.argsort(keys, kind="stable").astype(np.int32)
                counts = np.bincount(keys, minlength=num_workers)
                maxq = max(int(counts.max()), 1)
                q = np.zeros((num_workers, maxq), np.int32)
                start = 0
                for w in range(num_workers):
                    q[w, : counts[w]] = order[start : start + counts[w]]
                    start += counts[w]
            self._queues[ck] = (
                host_to_replicated(q, self.mesh),
                counts.astype(np.int64),
            )
        return self._queues[ck]

    def packed(self, route_key: str | None, num_workers: int):
        """Queue-ordered packed row matrix, or ``None`` when not packable.

        When every column is 1-D with a 4-byte dtype, batch building can be
        ONE gather instead of one-per-column-plus-queue-indirection: rows
        are pre-gathered in queue order and bit-packed channel-wise into a
        ``(num_workers * max_queue, C)`` int32 matrix (built on device from
        the resident columns — no host traffic). Returns
        ``(matrix, names, dtypes)`` for :meth:`DeviceEpochPlan` to unpack.
        """
        ck = (route_key, num_workers)
        cache = getattr(self, "_packed", None)
        if cache is None:
            cache = self._packed = {}
        if ck not in cache:
            items = list(self.columns.items())
            _, host_counts = self.queues(route_key, num_workers)
            # Skewed routing pads every queue to the longest one; cap the
            # HBM blowup of the packed matrix at ~2x the raw columns.
            blowup = num_workers * int(host_counts.max()) / max(self.n, 1)
            if blowup <= 2.0 and all(
                v.ndim == 1 and v.dtype.itemsize == 4 for _, v in items
            ):
                queues, _ = self.queues(route_key, num_workers)
                names = [k for k, _ in items]
                dtypes = [v.dtype for _, v in items]

                def build(queues, columns):
                    flat = queues.reshape(-1)
                    chans = [
                        jax.lax.bitcast_convert_type(
                            jnp.take(columns[k], flat), jnp.int32
                        )
                        for k in names
                    ]
                    return jnp.stack(chans, axis=-1)

                arr = jax.jit(
                    build,
                    out_shardings=NamedSharding(self.mesh, P()),
                )(queues, self.columns)
                cache[ck] = (arr, names, dtypes)
            else:
                cache[ck] = None
        return cache[ck]

    def column_names(self):
        return list(self.columns)


class DeviceEpochPlan:
    """Epoch traversal geometry over a :class:`DeviceDataset`.

    Owns the per-worker queues, the shuffle parameters, and the pure traced
    function :meth:`local_batch_at` that conjures worker ``w``'s step-``t``
    batch from the resident columns. Consumed either step-at-a-time inside
    the driver's compiled loop (``Trainer.run_indexed`` — ingest fused into
    the jit, one dispatch per epoch) or materialized chunkwise by
    :func:`device_epoch_chunks`.

    Coverage contract (all shuffle modes): every example exactly once per
    epoch; positions past a worker's queue produce weight-0 padding rows.
    """

    def __init__(self, dataset: DeviceDataset, *, num_workers: int,
                 local_batch: int, route_key: str | None = None,
                 shuffle: str | None = "interleave", seed: int = 0,
                 sync_every: int | None = None, pack: bool = True):
        if shuffle not in (None, "interleave", "sort"):
            raise ValueError(f"unknown shuffle mode {shuffle!r}")
        self.dataset = dataset
        self.num_workers = num_workers
        self.local_batch = local_batch
        self.route_key = route_key
        self.shuffle = shuffle
        self.seed = seed
        self.sync_every = sync_every
        self.pack = pack

        queues, host_counts = dataset.queues(route_key, num_workers)
        self._queues = queues
        self._host_counts = host_counts
        self.maxq = queues.shape[1]
        max_count = int(host_counts.max())
        self._mesh = dataset.mesh
        # ~sqrt(count) rows, power of two (shift/mask div), capped.
        self.grid_r = 1 << max(0, min(_GRID_ROWS_MAX.bit_length() - 1,
                                      int(max(max_count, 1)).bit_length() // 2))
        self.grid_c = np.maximum(
            -(-host_counts // self.grid_r), 1
        ).astype(np.int32)
        self.grid_m = (self.grid_r * self.grid_c).astype(np.int32)
        self.counts = host_counts.astype(np.int32)

        # Each worker scans [0, r*ceil(count/r)) — at most count + grid_r.
        scan_len = max_count + (self.grid_r if shuffle == "interleave" else 0)
        steps = max(1, -(-scan_len // local_batch))
        if sync_every:
            steps = -(-steps // sync_every) * sync_every
        self.steps_per_epoch = steps

        # Transposed-epoch fast path: for interleave (and stream-order) the
        # per-step batch gather is replaced by a once-per-epoch REGULAR
        # relayout. The bijection qpos = (pos%r)*c + pos//r + off (mod m) is
        # exactly "roll rows by -off, view as (r, c), transpose": batches
        # then read CONTIGUOUS slices of the transposed buffer. The per-step
        # random gather of B rows is per-row-transaction bound on TPU
        # (~11ns/row measured on a 20M-row matrix = ~360us/step at B=32k);
        # the transpose is bandwidth bound (~1ms/epoch for 240MB) and the
        # contiguous dynamic_slice is ~free.
        self._tbuf_jit = None
        if pack and shuffle in (None, "interleave"):
            packed = dataset.packed(route_key, num_workers)
            if packed is not None:
                self._tbuf_jit = self._make_tbuf_jit(packed[0].shape[1])

        if shuffle == "sort":
            maxq, counts, W = self.maxq, jnp.asarray(self.counts), num_workers
            # Key-data shape of the active prng impl (eval_shape: traced,
            # never executed — no device work at plan init either).
            self._key_data_shape = jax.eval_shape(
                lambda: jax.random.key_data(jax.random.key(0))
            ).shape

            def mk_perm(key_data):
                key = jax.random.wrap_key_data(key_data)
                keys = jax.random.split(key, W)
                u = jax.vmap(lambda k: jax.random.uniform(k, (maxq,)))(keys)
                u = jnp.where(jnp.arange(maxq)[None, :] < counts[:, None],
                              u, jnp.inf)
                return jnp.argsort(u, axis=1).astype(jnp.int32)

            # jitted ONCE per plan — a fresh jit per epoch would recompile
            # the (W, maxq) argsort program every epoch. Takes raw key data
            # (a plain numpy array, implicitly replicated) so the path works
            # under multi-controller JAX too.
            self._perm_jit = jax.jit(
                mk_perm,
                out_shardings=NamedSharding(dataset.mesh, P()),
            )

    def _make_tbuf_jit(self, num_channels: int):
        """Jitted per-epoch builder of the transposed row buffer.

        ``(packed rows, per-worker offsets) -> (W, steps*B, C)`` where entry
        ``[w, pos]`` holds worker ``w``'s step-order example at position
        ``pos`` — i.e. ``packed[w*maxq + (bij(pos) + off_w) mod m_w]`` — so
        :meth:`local_batch_at` reads plain contiguous slices. Built from
        regular ops only (slice, roll, transpose, pad): no gathers.
        """
        W, r, maxq = self.num_workers, self.grid_r, self.maxq
        out_rows = self.steps_per_epoch * self.local_batch
        C = num_channels

        def build(packed_mat, off_w):
            outs = []
            for w in range(W):
                c_w = int(self.grid_c[w])
                m_w = int(self.grid_m[w])
                seg = packed_mat[w * maxq : (w + 1) * maxq]
                if m_w <= maxq:
                    rows = seg[:m_w]
                else:
                    rows = jnp.concatenate(
                        [seg, jnp.zeros((m_w - maxq, C), seg.dtype)]
                    )
                if self.shuffle == "interleave":
                    rolled = jnp.roll(rows, -off_w[w], axis=0)
                    tb = (
                        rolled.reshape(r, c_w, C)
                        .transpose(1, 0, 2)
                        .reshape(m_w, C)
                    )
                else:  # stream order: contiguous already, just pad
                    tb = rows
                if m_w < out_rows:
                    tb = jnp.concatenate(
                        [tb, jnp.zeros((out_rows - m_w, C), tb.dtype)]
                    )
                outs.append(tb[:out_rows])
            return jnp.stack(outs)

        return jax.jit(
            build, out_shardings=NamedSharding(self._mesh, P())
        )

    def calls_per_epoch(self, steps_per_call: int) -> int:
        """Compiled calls covering one epoch at ``steps_per_call`` steps
        each (the final call's trailing steps are weight-0 padding).
        One definition shared by the per-chunk driver
        (``Trainer.run_indexed``) and the K-chunk megastep
        (``fps_tpu.core.megastep``), so their chunk grids — and with
        them the per-(epoch, chunk) PRNG derivation — cannot drift."""
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        return -(-self.steps_per_epoch // steps_per_call)

    def _epoch_rng(self, tag: int, epoch: int) -> np.random.Generator:
        """Deterministic host rng for (tag, seed, epoch) — accepts negative
        seeds (SeedSequence rejects negative entropy, so mask to 64 bits)."""
        return np.random.default_rng(
            (tag, self.seed & ((1 << 64) - 1), epoch)
        )

    def epoch_args(self, epoch: int):
        """Device operands for one epoch (replicated pytree)."""
        mesh = self.dataset.mesh
        off_w = np.zeros(self.num_workers, np.int32)
        perm = None
        if self.shuffle == "interleave":
            # Host-side draw: deterministic in (seed, epoch) and identical
            # on every controller. A jax.random draw here would cost a
            # device dispatch PLUS a blocking int() transfer per epoch —
            # measured ~165 ms on the tunneled chip (the per-sync floor),
            # serialized between epochs for a one-integer result.
            off = int(self._epoch_rng(0x0FF5E7, epoch).integers(
                0, max(int(self._host_counts.max()), 1)
            ))
            off_w = (off % self.grid_m.astype(np.int64)).astype(np.int32)
        elif self.shuffle == "sort":
            # Same host-side-determinism reasoning: raw key data built in
            # numpy, sized for the ACTIVE prng impl (threefry (2,),
            # rbg/unsafe_rbg (4,) — probed via eval_shape at plan init, no
            # device round trip anywhere on this path).
            kd = self._epoch_rng(0x5037, epoch).integers(
                0, 1 << 32, self._key_data_shape, dtype=np.uint32
            )
            perm = self._perm_jit(kd)
        if perm is None:
            perm = host_to_replicated(np.zeros((1, 1), np.int32), mesh)
        packed = (self.dataset.packed(self.route_key, self.num_workers)
                  if self.pack else None)
        args = {
            "columns": self.dataset.columns,
            "queues": self._queues,
            "off_w": host_to_replicated(off_w, mesh),
            "perm": perm,
        }
        if self._tbuf_jit is not None:
            args["tbuf"] = self._tbuf_jit(packed[0], off_w)
        elif packed is not None:
            args["packed"] = packed[0]
        return args

    # -- traced: called inside jit (driver scan or chunk builder) ----------

    def local_batch_at(self, args, w, t):
        """Worker ``w``'s step-``t`` batch: dict of ``(local_batch,)`` leaves
        plus the ``weight`` mask. Pure/traceable; ``w`` and ``t`` are traced
        int32 scalars."""
        pos = t * self.local_batch + jnp.arange(self.local_batch,
                                                dtype=jnp.int32)
        cnt = jnp.asarray(self.counts)[w]
        if self.shuffle == "interleave":
            c = jnp.asarray(self.grid_c)[w]
            m = jnp.asarray(self.grid_m)[w]
            x = (pos % self.grid_r) * c + pos // self.grid_r  # bijection on [0, m)
            qpos = x + args["off_w"][w]
            qpos = jnp.where(qpos >= m, qpos - m, qpos)
            valid = (pos < m) & (qpos < cnt)
        elif self.shuffle == "sort":
            qpos = jnp.take(args["perm"].reshape(-1),
                            w * self.maxq + jnp.clip(pos, 0, self.maxq - 1))
            valid = pos < cnt
        else:
            qpos = pos
            valid = pos < cnt
        if "tbuf" in args:
            # Transposed fast path: batch = one contiguous slice. The buffer
            # already encodes the shuffle bijection + offset; ``valid`` was
            # computed from the same (qpos, cnt) math above.
            _, names, dtypes = self.dataset.packed(
                self.route_key, self.num_workers
            )
            C = len(names)
            rows = jax.lax.dynamic_slice(
                args["tbuf"],
                (w, t * self.local_batch, 0),
                (1, self.local_batch, C),
            ).reshape(self.local_batch, C)
            batch = {
                k: jax.lax.bitcast_convert_type(rows[:, i], dt)
                for i, (k, dt) in enumerate(zip(names, dtypes))
            }
            batch["weight"] = valid.astype(jnp.float32)
            return batch
        slot = w * self.maxq + jnp.clip(qpos, 0, self.maxq - 1)
        if "packed" in args:
            # One gather of queue-ordered packed rows, then per-channel
            # bitcasts — replaces the queue indirection + one gather per
            # column (measured ~3x faster batch construction).
            _, names, dtypes = self.dataset.packed(
                self.route_key, self.num_workers
            )
            rows = jnp.take(args["packed"], slot, axis=0)  # (B, C) int32
            batch = {
                k: jax.lax.bitcast_convert_type(rows[:, i], dt)
                for i, (k, dt) in enumerate(zip(names, dtypes))
            }
        else:
            row = jnp.take(args["queues"].reshape(-1), slot)
            batch = {k: jnp.take(col, row, axis=0)
                     for k, col in args["columns"].items()}
        batch["weight"] = valid.astype(jnp.float32)
        return batch

    def _chunk_builder(self, steps_per_chunk: int):
        """Jitted (epoch_args, start_step) -> (T, B) chunk, cached per plan."""
        cache = getattr(self, "_builders", None)
        if cache is None:
            cache = self._builders = {}
        if steps_per_chunk not in cache:
            out_sharding = NamedSharding(
                self.dataset.mesh,
                P(None, None, WORKER_AXES) if self.sync_every
                else P(None, WORKER_AXES),
            )
            W, B, s = self.num_workers, self.local_batch, self.sync_every

            def build(args, start_step):
                ts = start_step + jnp.arange(steps_per_chunk, dtype=jnp.int32)
                ws = jnp.arange(W, dtype=jnp.int32)
                chunk = jax.vmap(
                    lambda t: jax.vmap(
                        lambda w: self.local_batch_at(args, w, t)
                    )(ws)
                )(ts)  # leaves: (T, W, B, ...)
                chunk = {
                    k: v.reshape((steps_per_chunk, W * B) + v.shape[3:])
                    for k, v in chunk.items()
                }
                if s:
                    chunk = {
                        k: v.reshape((steps_per_chunk // s, s) + v.shape[1:])
                        for k, v in chunk.items()
                    }
                return chunk

            cache[steps_per_chunk] = jax.jit(
                build,
                out_shardings={
                    k: out_sharding
                    for k in list(self.dataset.columns) + ["weight"]
                },
            )
        return cache[steps_per_chunk]


_UNSET = object()  # distinguishes omitted kwargs from explicit defaults


def device_epoch_chunks(
    dataset: DeviceDataset,
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    route_key=_UNSET,
    sync_every=_UNSET,
    seed=_UNSET,
    epochs: int = 1,
    start_epoch: int = 0,
    shuffle=_UNSET,
    plan: DeviceEpochPlan | None = None,
) -> Iterator[dict]:
    """Yield device-resident chunks for ``epochs`` passes over the data.

    Chunk contract matches :func:`fps_tpu.core.ingest.epoch_chunks`: leaves
    shaped ``(T, B)`` (or ``(R, s, B)`` when ``sync_every`` is set) with a
    ``weight`` mask column, batch dim worker-major and sharded over the
    worker axes — but every leaf is already a committed jax array on the
    mesh, so the driver moves no bytes. Pass an existing ``plan`` to reuse
    its compiled chunk builder across calls, with ``start_epoch`` selecting
    which epoch's shuffle the pass replays (epoch identity is a host-side
    deterministic draw keyed on ``(plan.seed, epoch)`` —
    ``DeviceEpochPlan._epoch_rng`` — so restarts are reproducible).
    """
    if plan is None:
        plan = DeviceEpochPlan(
            dataset, num_workers=num_workers, local_batch=local_batch,
            route_key=None if route_key is _UNSET else route_key,
            shuffle="interleave" if shuffle is _UNSET else shuffle,
            seed=0 if seed is _UNSET else seed,
            sync_every=None if sync_every is _UNSET else sync_every,
        )
    else:
        # An explicit plan carries its own geometry; silently ignoring
        # disagreeing kwargs would hand the caller the plan's geometry with
        # no warning (mirrors run_indexed's sync_every consistency check).
        # Only kwargs the caller actually passed are compared (_UNSET marks
        # omissions), and sync_every is truthiness-normalized like the
        # driver does (0 and None both mean fully synchronous).
        mismatches = {
            k: (got, want)
            for k, got, want in (
                ("num_workers", num_workers, plan.num_workers),
                ("local_batch", local_batch, plan.local_batch),
                ("route_key", route_key, plan.route_key),
                ("shuffle", shuffle, plan.shuffle),
                ("seed", seed, plan.seed),
                (
                    "sync_every",
                    _UNSET if sync_every is _UNSET else (sync_every or None),
                    plan.sync_every or None,
                ),
            )
            if got is not _UNSET and got != want
        }
        if mismatches:
            raise ValueError(
                "explicit plan disagrees with kwargs: "
                + ", ".join(
                    f"{k}={got!r} but plan.{k}={want!r}"
                    for k, (got, want) in mismatches.items()
                )
            )
    if plan.sync_every and steps_per_chunk % plan.sync_every:
        raise ValueError("steps_per_chunk must be a multiple of sync_every")

    def _chunks():
        build = plan._chunk_builder(steps_per_chunk)
        steps_total = (
            -(-plan.steps_per_epoch // steps_per_chunk) * steps_per_chunk
        )
        for epoch in range(start_epoch, start_epoch + epochs):
            args = plan.epoch_args(epoch)
            for start in range(0, steps_total, steps_per_chunk):
                yield build(args, np.int32(start))

    return _chunks()
