"""Resilience layer: step-health guards, snapshot integrity, rollback.

A production PS serving live traffic must absorb two failure classes the
reference (and our own seed) could not:

* **poison updates** — one bad batch (corrupt ingest row, overflowed
  feature, adversarial input) pushes NaN/Inf or norm-exploded deltas;
  under the additive server fold a single such push irreversibly destroys
  every row it touches, and the damage then spreads through every pull.
* **torn snapshots** — a crash or disk fault mid-write (or bit rot at
  rest) leaves the newest ``.npz`` unreadable; a restore that can only
  try the latest file turns one bad snapshot into an unrecoverable job.

This module holds the policy objects and pure helpers; the wiring lives in
:mod:`fps_tpu.core.driver` (on-device guard + host-loop rollback) and
:mod:`fps_tpu.core.checkpoint` (per-array checksums + fallback restore).
Everything here is dependency-light (jax/numpy only) so both layers can
import it without cycles. Failure injection for tests lives in
:mod:`fps_tpu.testing.chaos`; the failure model is documented in
``docs/resilience.md``.

Design constraint: ``TrainerConfig.guard is None`` (the default) must
compile to the *identical* program as a guard-free build — every branch
below is resolved at trace time, so the health machinery costs nothing
when it is off (tested via compiled-HLO comparison in
``tests/test_resilience.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
Pytree = Any

HEALTH_KEY = "health"

# Health-channel entry name for the worker-LOCAL state plane (the guard's
# second coverage surface — GuardConfig.local). Lives next to the
# per-table entries; the driver rejects a store table with this name when
# the local guard is on, so the two planes can never collide.
LOCAL_STATE_KEY = "local_state"

# Out-channel entry name for the two-tier hot-storage telemetry
# (per-table hot/pulled row counts + pending-delta magnitude — the
# parameter-plane staleness gauge riding the health channel's transport).
# Mounted by the driver with the same dict-out-channel + collision
# contract as HEALTH_KEY; rollback snapshots taken under a hot tier
# carry the replica entries too (``tree_copy`` over the whole tables
# dict), so a quarantine restores replica, canonical table, and — by the
# flush-reconcile boundary invariant — an implicitly empty delta buffer
# as one consistent unit.
HOT_TIER_KEY = "hot_tier"

GUARD_MODES = ("observe", "mask")


class SnapshotCorruptionError(RuntimeError):
    """A snapshot failed its integrity check (truncated, bit-flipped, or
    otherwise unreadable). Raised by the checkpoint layer when the caller
    pinned an explicit step; auto-resolved restores fall back to the
    previous surviving snapshot instead."""


class PoisonedStreamError(RuntimeError):
    """The host-loop rollback policy exhausted its quarantine budget —
    the input stream keeps producing poisoned chunks."""


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """On-device push-delta health guard (``TrainerConfig.guard``).

    Inside the compiled scan, every table's push deltas are screened per
    row *before* they reach the server fold:

    * rows with any non-finite element count as ``nonfinite``;
    * rows whose L2 norm exceeds ``norm_limit`` (when set) count as
      ``norm`` — the early-warning tier for divergence that is still
      finite;
    * in ``mode="mask"``, offending rows are dropped (id → ``-1``, delta
      → 0) so a poison batch degrades to a lost update instead of table
      death; ``mode="observe"`` only counts, leaving the stream
      byte-identical (pair it with a host-loop
      :class:`RollbackPolicy` to quarantine instead).

    The per-step, per-table counts ride the worker ``out`` channel as a
    ``"health"`` entry (psum'd across workers like every other metric), so
    surfacing them costs one int32 reduction per table per step — noise
    next to the pull/push collectives.

    Frozen (hashable): the guard is part of the trainer's compile-cache
    key, like ``push_delay`` and the ops backend.
    """

    mode: str = "mask"
    # Per-row L2 norm ceiling for push deltas; None disables the norm
    # tier (non-finite screening is always on while a guard is set).
    norm_limit: float | None = None
    # Restrict guarding to these tables (None = all). Tables outside the
    # set pass through untouched and report no health entry.
    tables: tuple[str, ...] | None = None
    # Extend screening to worker-LOCAL state updates (MF user factors,
    # any float leaf of the local_state pytree): after each step, rows
    # whose NEW value is non-finite — or whose update delta exceeds
    # ``norm_limit`` — are counted onto a ``"local_state"`` health entry
    # and, in mask mode, reverted to their pre-step values. Closes the
    # MF-style gap where mask mode screens PS pushes but the local
    # scatter still absorbs NaN. Off by default: ``local=False`` traces
    # the exact same program as before (the ``tables`` filter does not
    # apply — local state has no table name).
    local: bool = False

    def __post_init__(self):
        if self.mode not in GUARD_MODES:
            raise ValueError(
                f"guard mode {self.mode!r} — expected one of {GUARD_MODES}"
            )
        if self.norm_limit is not None and not self.norm_limit > 0:
            raise ValueError(f"norm_limit must be > 0, got {self.norm_limit}")
        if self.tables is not None:
            # Coerce here so a list fails at construction time, not as an
            # unhashable-type error deep in the trainer's compile cache.
            object.__setattr__(self, "tables", tuple(self.tables))


def as_guard(guard) -> GuardConfig | None:
    """Coerce ``TrainerConfig.guard`` (None | str | GuardConfig)."""
    if guard is None or isinstance(guard, GuardConfig):
        return guard
    if isinstance(guard, str):
        return GuardConfig(mode=guard)
    raise TypeError(
        f"guard must be None, 'observe'/'mask', or a GuardConfig; "
        f"got {type(guard).__name__}"
    )


def guard_pushes(
    pushes: Mapping[str, tuple[Array, Array]], guard: GuardConfig
) -> tuple[dict[str, tuple[Array, Array]], dict[str, dict[str, Array]]]:
    """Screen per-table ``(ids, deltas)`` pushes; trace-time static policy.

    Returns ``(guarded_pushes, health)`` where ``health[table]`` holds
    scalar int32 counts ``{"nonfinite", "norm", "masked"}`` for THIS
    worker's batch (the driver psums them into global per-step counts).
    Padding rows (id ``-1``) never count — they were already dropped.

    In mask mode both the id (→ ``-1``) and the delta (→ 0) of a bad row
    are cleared — and non-finite deltas are zeroed even on rows that were
    ALREADY padding (a poisoned batch value can propagate NaN into a
    weight-0 row's delta): the gathered/XLA routes drop dead rows by
    select, but the lane-packed MXU routes multiply every delta by its
    0/1 indicator, and ``0 * NaN`` would poison whole row tiles. Only
    live rows count toward health (the padding row's poison always has a
    live sibling in the same batch).
    """
    out_pushes: dict[str, tuple[Array, Array]] = {}
    health: dict[str, dict[str, Array]] = {}
    for name, (ids, deltas) in pushes.items():
        if guard.tables is not None and name not in guard.tables:
            out_pushes[name] = (ids, deltas)
            continue
        live = ids >= 0
        finite = jnp.all(jnp.isfinite(deltas), axis=-1)
        nonfinite = live & ~finite
        if guard.norm_limit is not None:
            # Compute the norm over zero-substituted rows so a NaN row
            # never double-counts (NaN comparisons are False anyway, but
            # keeping the operands finite is cheaper to reason about).
            sq = jnp.sum(
                jnp.where(finite[:, None], deltas, 0.0).astype(jnp.float32)
                ** 2,
                axis=-1,
            )
            exploded = live & finite & (sq > guard.norm_limit**2)
        else:
            exploded = jnp.zeros_like(nonfinite)
        bad = nonfinite | exploded
        counts = {
            "nonfinite": jnp.sum(nonfinite, dtype=jnp.int32),
            "norm": jnp.sum(exploded, dtype=jnp.int32),
        }
        if guard.mode == "mask":
            ids = jnp.where(bad, jnp.asarray(-1, ids.dtype), ids)
            scrub = bad | ~finite  # non-finite padding rows too (see above)
            deltas = jnp.where(
                scrub[:, None], 0.0, deltas
            ).astype(deltas.dtype)
            counts["masked"] = jnp.sum(bad, dtype=jnp.int32)
        else:
            counts["masked"] = jnp.zeros((), jnp.int32)
        out_pushes[name] = (ids, deltas)
        health[name] = counts
    return out_pushes, health


def guard_local_state(
    old: Pytree, new: Pytree, guard: GuardConfig, touched=None
) -> tuple[Pytree, dict[str, Array] | None]:
    """Screen a step's worker-LOCAL state update; trace-time static policy.

    The local plane has no ``(ids, deltas)`` stream to intercept — worker
    logics scatter into their local arrays directly inside ``step`` — so
    the guard screens the *effect*: for every inexact (float) leaf, a
    "row" is one index along axis 0 (the whole array for 0-d leaves), and

    * rows of ``new`` containing any non-finite element count as
      ``nonfinite``;
    * rows whose update delta ``new - old`` has L2 norm over
      ``guard.norm_limit`` (when set) count as ``norm``;
    * in ``mode="mask"`` offending rows REVERT to their pre-step values
      (the scatter update degrades to a lost update, mirroring the push
      guard's dropped rows); ``"observe"`` only counts.

    ``touched`` (from ``WorkerLogic.touched_local_rows``): one entry per
    flattened leaf — an int id array (``-1`` ignored) restricting that
    leaf's ROW screening (nonfinite + norm tiers, and mask-mode reverts)
    to the rows this step can actually write, or ``None`` for the
    full-leaf screen. Untouched rows are still covered by a LEAF-tier
    non-finite net: any non-finite row outside the touched set counts as
    ``nonfinite`` (it cannot be masked — its pre-step value IS its
    post-step value, so there is nothing to revert to), so a poisoned
    row can never hide outside the ids. Duplicate touched ids count per
    occurrence (the push guard's per-batch-row convention) and revert
    deterministically — every occurrence writes the same row value.

    Returns ``(guarded_new, counts)`` with the same scalar int32
    ``{"nonfinite", "norm", "masked"}`` schema as :func:`guard_pushes`
    (the driver mounts it under :data:`LOCAL_STATE_KEY`), or
    ``(new, None)`` when the pytree has no inexact leaves — an empty
    local state costs nothing and adds no health entry.

    Caveat: the delta-norm tier is computed against ``old``; if an
    earlier *observe*-mode step already let non-finite values into a row,
    that row's delta is non-finite and lands in the ``nonfinite`` tier
    (reverting cannot resurrect a row that was never finite).
    """
    old_leaves, treedef = jax.tree.flatten(old)
    new_leaves, new_treedef = jax.tree.flatten(new)
    if treedef != new_treedef:
        raise ValueError(
            "guard.local requires the worker step to preserve the "
            f"local_state pytree structure (got {treedef} -> {new_treedef})"
        )
    if touched is not None:
        touched = list(touched)
        if len(touched) != len(new_leaves):
            raise ValueError(
                "touched_local_rows must return one entry per flattened "
                f"local-state leaf ({len(new_leaves)}), got {len(touched)}"
            )
    zero = jnp.zeros((), jnp.int32)
    counts = {"nonfinite": zero, "norm": zero, "masked": zero}
    guarded = False
    out_leaves = []
    for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
        if not (hasattr(n, "dtype") and jnp.issubdtype(n.dtype, jnp.inexact)):
            out_leaves.append(n)
            continue
        guarded = True
        t = touched[i] if touched is not None else None
        if t is not None and jnp.ndim(n) >= 1:
            n, leaf_counts = _guard_rows_touched(o, n, guard, t)
        else:
            n, leaf_counts = _guard_rows_full(o, n, guard)
        for k, v in leaf_counts.items():
            counts[k] = counts[k] + v
        out_leaves.append(n)
    if not guarded:
        return new, None
    return jax.tree.unflatten(treedef, out_leaves), counts


def _guard_rows_full(o, n, guard: GuardConfig):
    """Whole-leaf row screen (every row; the ``touched=None`` path)."""
    axes = tuple(range(1, jnp.ndim(n)))
    finite = jnp.all(jnp.isfinite(n), axis=axes)
    nonfinite = ~finite
    if guard.norm_limit is not None:
        # Delta norm over zero-substituted rows, like guard_pushes:
        # a non-finite row must not double-count through the norm tier.
        delta = jnp.where(
            finite if not axes else jnp.expand_dims(
                finite, tuple(range(1, jnp.ndim(n)))),
            (n - o).astype(jnp.float32), 0.0,
        )
        sq = jnp.sum(delta * delta, axis=axes)
        exploded = finite & (sq > guard.norm_limit**2)
    else:
        exploded = jnp.zeros_like(nonfinite)
    bad = nonfinite | exploded
    counts = {
        "nonfinite": jnp.sum(nonfinite, dtype=jnp.int32),
        "norm": jnp.sum(exploded, dtype=jnp.int32),
        "masked": jnp.zeros((), jnp.int32),
    }
    if guard.mode == "mask":
        revert = bad if not axes else jnp.expand_dims(
            bad, tuple(range(1, jnp.ndim(n))))
        n = jnp.where(revert, o, n).astype(n.dtype)
        counts["masked"] = jnp.sum(bad, dtype=jnp.int32)
    return n, counts


def _guard_rows_touched(o, n, guard: GuardConfig, t):
    """Ids-aware row screen: gather the touched rows, screen THEM
    (nonfinite + norm + mask-mode revert via a drop-mode scatter), then
    run the leaf-tier net — a non-finite row outside the touched set
    still counts as ``nonfinite`` (but cannot be reverted; see
    :func:`guard_local_state`)."""
    rows = n.shape[0]
    t = jnp.asarray(t).reshape(-1).astype(jnp.int32)
    # Out-of-range ids are inert like -1: the clamped gather would
    # otherwise screen (and count reverts against) the LAST row once
    # per stray id while the drop-scatter discards the revert anyway.
    valid = (t >= 0) & (t < rows)
    safe = jnp.where(valid, t, 0)  # in-bounds gather index for -1 slots
    idx = jnp.where(valid, t, rows)  # out-of-bounds -> dropped by scatter
    n_t = jnp.take(n, safe, axis=0)
    o_t = jnp.take(o, safe, axis=0)
    axes = tuple(range(1, jnp.ndim(n_t)))
    finite_t = jnp.all(jnp.isfinite(n_t), axis=axes)
    nonfinite_t = valid & ~finite_t
    if guard.norm_limit is not None:
        delta = jnp.where(
            jnp.expand_dims(finite_t, axes) if axes else finite_t,
            (n_t - o_t).astype(jnp.float32), 0.0,
        )
        sq = jnp.sum(delta * delta, axis=axes)
        exploded_t = valid & finite_t & (sq > guard.norm_limit**2)
    else:
        exploded_t = jnp.zeros_like(nonfinite_t)
    bad_t = nonfinite_t | exploded_t
    counts = {
        "nonfinite": jnp.sum(nonfinite_t, dtype=jnp.int32),
        "norm": jnp.sum(exploded_t, dtype=jnp.int32),
        "masked": jnp.zeros((), jnp.int32),
    }
    if guard.mode == "mask":
        revert = jnp.expand_dims(bad_t, axes) if axes else bad_t
        repl = jnp.where(revert, o_t, n_t).astype(n.dtype)
        n = n.at[idx].set(repl, mode="drop")
        counts["masked"] = jnp.sum(bad_t, dtype=jnp.int32)
    # Leaf-tier net: non-finite rows OUTSIDE the touched set (stale
    # poison from an observe-mode step, a poisoned restore, bit rot in
    # host staging) are counted — detection must not depend on the ids.
    touched_mask = jnp.zeros((rows,), bool).at[idx].set(True, mode="drop")
    finite_rows = jnp.all(jnp.isfinite(n), axis=tuple(range(1, jnp.ndim(n))))
    counts["nonfinite"] = counts["nonfinite"] + jnp.sum(
        ~finite_rows & ~touched_mask, dtype=jnp.int32)
    return n, counts


def health_total(metrics: Pytree) -> int:
    """Total poison events in a chunk/epoch's HOST metrics pytree.

    Sums the ``nonfinite`` and ``norm`` counters of every table over every
    step (``masked`` is derived from those two, so it is excluded — it
    would double-count). Returns 0 when no health channel is present
    (guard off).
    """
    h = metrics.get(HEALTH_KEY) if isinstance(metrics, Mapping) else None
    if not h:
        return 0
    total = 0
    for counters in h.values():
        for kind in ("nonfinite", "norm"):
            if kind in counters:
                total += int(np.sum(np.asarray(counters[kind])))
    return total


def health_by_segment(metrics: Pytree, segments: int,
                      steps_per_segment: int) -> list[int]:
    """Per-segment poison totals of one megastep's HOST metrics pytree.

    The megastep driver (``fps_tpu.core.megastep``) dispatches
    ``segments`` in-graph chunk segments of ``steps_per_segment`` steps
    in one call; adjudication happens at megastep granularity, but the
    quarantine record should still name WHICH in-graph chunk reported
    poison. Splits the stacked per-step counters on the segment grid
    (the final, trimmed megastep may cover fewer rows — trailing
    segments then report 0) and sums ``nonfinite`` + ``norm`` per
    segment, mirroring :func:`health_total`'s counting rule.
    """
    h = metrics.get(HEALTH_KEY) if isinstance(metrics, Mapping) else None
    totals = [0] * segments
    if not h:
        return totals
    for counters in h.values():
        for kind in ("nonfinite", "norm"):
            if kind not in counters:
                continue
            v = np.asarray(counters[kind])
            if not v.ndim:
                totals[0] += int(v)
                continue
            for i in range(segments):
                sl = v[i * steps_per_segment:(i + 1) * steps_per_segment]
                totals[i] += int(np.sum(sl))
    return totals


@dataclasses.dataclass
class RollbackPolicy:
    """Host-loop degradation policy for ``fit_stream`` / ``run_indexed``.

    When a chunk/epoch's health channel reports poison (any nonzero
    ``nonfinite``/``norm`` count), the driver restores the state captured
    just before that chunk ran, records the chunk index in
    :attr:`quarantined`, and continues with the next chunk — the PRNG and
    shuffle streams are untouched because both key off the chunk/epoch
    index, not off how many chunks actually applied.

    Requires ``TrainerConfig.guard`` (either mode: ``"observe"`` gives
    pure quarantine semantics; ``"mask"`` would normally make rollback
    unnecessary, but combining them quarantines any chunk that needed
    masking at all). Each guarded chunk pays one on-device state copy
    (the pre-chunk snapshot must survive buffer donation) and one
    metrics host-sync — this is a degradation mode, not a fast path.

    ``preset`` indices are skipped OUTRIGHT — the chunk/epoch is consumed
    from the stream but never dispatched (no state copy, no metrics
    entry); PRNG/shuffle streams key off the index, so later work is
    unaffected. This is how quarantine decisions survive a process
    restart: the run supervisor (``fps_tpu.supervise``) persists the
    poisoned indices next to the checkpoint dir and the restarted child
    preloads them here, so a *deterministic* poison batch cannot crash-
    loop the run. A preset-only policy (no guard) is legal — it skips
    without needing the health channel.
    """

    # Quarantine budget: exceeding it raises PoisonedStreamError (a stream
    # that is ALL poison is an ingest bug, not a transient).
    max_rollbacks: int = 8
    # Chunk/epoch indices rolled back so far (mutated by the driver).
    quarantined: list = dataclasses.field(default_factory=list)
    # Indices quarantined by a PREVIOUS attempt (carried across restarts
    # by the supervisor): skipped without dispatch.
    preset: frozenset = frozenset()
    # Preset indices actually skipped this run (mutated by the driver).
    skipped: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        # Coerce lists/tuples (the supervisor state file round-trips
        # through JSON) so membership tests are O(1) and hashable-safe.
        self.preset = frozenset(int(i) for i in self.preset)

    def skip(self, index: int) -> None:
        """Record one preset-quarantined index skipped without dispatch
        (journal-trailed like :meth:`record`, but no budget: these chunks
        were already adjudicated by a previous attempt)."""
        self.skipped.append(index)
        from fps_tpu.obs import events as _obs_events

        _obs_events.emit("preset_skip", index=int(index),
                         total=len(self.skipped))

    def record(self, index: int) -> None:
        """Record a quarantined index; raises once the budget is exceeded.
        The index is appended BEFORE the raise so the quarantine log is
        complete for a caller that catches PoisonedStreamError. Callers
        (the driver) restore last-good state before calling this, so the
        raise never strands donated buffers."""
        self.quarantined.append(index)
        # Journal trail (fps_tpu.obs.events — stdlib-only, no cycle; no-op
        # when no process-default recorder is installed).
        from fps_tpu.obs import events as _obs_events

        _obs_events.emit("rollback", index=int(index),
                         total=len(self.quarantined),
                         budget=self.max_rollbacks)
        if len(self.quarantined) > self.max_rollbacks:
            _obs_events.emit("poisoned_stream_abort",
                             quarantined=list(self.quarantined),
                             budget=self.max_rollbacks)
            raise PoisonedStreamError(
                f"rollback budget exhausted ({self.max_rollbacks}); "
                f"quarantined chunks: {self.quarantined}"
            )


def tree_copy(tree: Pytree) -> Pytree:
    """Fresh on-device buffers for every array leaf — a pre-chunk snapshot
    that survives the training call's donation of the originals."""
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree
    )


# ---------------------------------------------------------------------------
# Snapshot integrity primitives (shared by checkpoint.py and the tests).
#
# One implementation, owned by the jax-FREE on-disk-contract module so
# the write path (checkpoint.py, via this re-export) and the serving
# plane's verifier can never drift — a fork here would make every fresh
# snapshot fail read-side verification.
# ---------------------------------------------------------------------------

from fps_tpu.core.snapshot_format import array_crc32  # noqa: E402,F401
