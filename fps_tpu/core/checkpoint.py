"""Model export, warm start, and periodic checkpointing.

Reference persistence surface being rebuilt (SURVEY.md §5 checkpoint row;
expected upstream ``src/main/scala/hu/sztaki/ilab/ps/FlinkParameterServer.scala``):

* **final model emission** — at end of job, ``ParameterServerLogic.close``
  streams every ``(paramId, value)`` pair out of each shard. Here:
  :func:`export_model` writes every table as a logical ``(num_ids, dim)``
  array (id order, padding rows stripped) to one ``.npz``.
* **warm start** — the ``transformWithModelLoad``-style overloads union a
  previously saved ``DataStream[(Int, P)]`` into the servers before/while
  training. Here: :func:`load_model` / :func:`load_rows` overwrite table
  rows from a saved model (whole table or an arbitrary id subset) directly
  in the sharded layout.
* **periodic snapshots** — the reference has none (Flink-era checkpointing
  does not cover iterative streams, so a failure loses server state).
  :class:`Checkpointer` snapshots the live tables + worker-local state every
  N chunks and restores them for resume — the leapfrog SURVEY.md §5 calls
  cheap on TPU because parameter state is just a sharded jax array.

Format: plain ``.npz``; no framework lock-in, loadable from numpy alone.
Tables are saved in *logical* id order, so a checkpoint taken on an S-shard
mesh restores onto any other shard count.

:class:`AsyncCheckpointer` is the drop-in double-buffered variant: the
device→host snapshot is captured synchronously, serialize+fsync+rename run
on a background writer thread, and ``flush()`` is the durability barrier
(the drivers call it at end of run). ``checkpoint_enqueued`` /
``checkpoint_saved`` journal events mark acceptance vs. durability.

The remaining synchronous cost — the device→host dump inside
:meth:`Checkpointer.save` (timed as ``checkpoint.dump_seconds``) — is
hidden by the overlapped host pipeline: ``Trainer.fit_stream`` with the
pipeline on takes an ON-DEVICE copy of the tables at the chunk boundary
(the double-buffering the PR-3 refinement called for) and runs ``save()``
against the copy after the next chunk has been dispatched, so the dump's
``device_get`` waits alongside device compute instead of in front of it
(``docs/performance.md``).
"""

from __future__ import annotations

import dataclasses
import errno as _errno_mod
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Mapping

import jax
import numpy as np

from fps_tpu.core import retry as _retry
from fps_tpu.core import snapshot_format
from fps_tpu.core.resilience import SnapshotCorruptionError, array_crc32
from fps_tpu.core.store import ParamStore, id_to_phys, rows_per_shard

Pytree = Any

_log = logging.getLogger("fps_tpu.checkpoint")


def _obs_event(etype: str, **fields) -> None:
    """Persistence events onto the process-default telemetry recorder
    (fps_tpu.obs.events) — the run journal's checkpoint trail. Lazy
    import + no-op when no recorder is installed, so this module adds no
    hard obs dependency and no cost when telemetry is off."""
    from fps_tpu.obs import events

    events.emit(etype, **fields)


def _obs_metric(kind: str, name: str, value: float, **labels) -> None:
    from fps_tpu.obs import events

    events.record_metric(kind, name, value, **labels)


# The on-disk contract (filename regex, npz key layout, per-array
# ``meta::crc`` integrity tags, the torn-file error set) lives in the
# jax-free :mod:`fps_tpu.core.snapshot_format` so the serving plane and
# the chaos injectors can share it without importing this (jax-laden)
# module; the historical names are re-exported here.
_SEP = snapshot_format.SEP  # npz key separator: kind::name
SNAPSHOT_RE = snapshot_format.SNAPSHOT_RE
SNAPSHOT_FMT = snapshot_format.SNAPSHOT_FMT
_CRC_PREFIX = snapshot_format.CRC_PREFIX
_IO_ERRORS = snapshot_format.IO_ERRORS


def _keys(z):
    """Key collection of an open npz OR a plain {key: array} dict (the
    verified-read path materializes entries before using these helpers)."""
    return z.files if hasattr(z, "files") else z


def _ls_leaves(z) -> list:
    """Local-state leaves from an npz/dict (touches only ls:: keys)."""
    leaves = []
    i = 0
    while f"ls{_SEP}{i}" in _keys(z):
        leaves.append(z[f"ls{_SEP}{i}"])
        i += 1
    return leaves


def _ls_format(z) -> str:
    key = f"meta{_SEP}ls_format"
    return str(z[key]) if key in _keys(z) else "raw"


# ---------------------------------------------------------------------------
# Model export (the reference's close()-time (id, param) stream).
# ---------------------------------------------------------------------------

def _table_arrays(store: ParamStore) -> dict[str, np.ndarray]:
    """All tables as npz entries, logical id order, padding stripped.

    Spec-driven by design: under two-tier hot storage the live tables
    dict also carries replicated hot-head entries (``hot_key(name)``,
    never in ``store.specs``) — a snapshot stays ONE canonical table per
    spec. The drivers flush-reconcile every compiled call, so at any
    save boundary the sharded table already folds all hot pushes;
    restore re-splits via ``Trainer._attach_hot``. A checkpoint written
    under the tier is therefore byte-compatible with (and restorable
    by) an untiered run of the same state.
    """
    from fps_tpu.core.store import is_hot_key

    assert not any(is_hot_key(name) for name in store.specs), (
        "hot-replica entries must never be registered as specs — the "
        "canonical sharded table is the only serialized form"
    )
    return {
        f"table{_SEP}{name}": store.dump_model(name)[1] for name in store.specs
    }


def export_model(store: ParamStore, path: str) -> None:
    """Write all tables, logical id order, padding stripped, to ``path``.npz."""
    _atomic_savez(path, _table_arrays(store))


def load_saved_model(path: str) -> dict[str, np.ndarray]:
    """Read a model saved by :func:`export_model` → ``{table: (n, dim)}``."""
    with np.load(path) as z:
        return {
            k.split(_SEP, 1)[1]: z[k] for k in z.files if k.startswith(f"table{_SEP}")
        }


# ---------------------------------------------------------------------------
# Warm start (transformWithModelLoad parity).
# ---------------------------------------------------------------------------

def load_rows(
    store: ParamStore, name: str, ids: np.ndarray, values: np.ndarray
) -> None:
    """Overwrite rows ``ids`` of table ``name`` with ``values``.

    The sharded-array equivalent of streaming ``(paramId, value)`` records
    into the servers: each row lands on its owning shard (owner-major cyclic
    layout), rows not mentioned keep their current (initialized or trained)
    values. Call after ``store.init(key)``.
    """
    if name not in store.tables:
        raise ValueError(f"table {name!r} not initialized; call store.init first")
    spec = store.specs[name]
    ids = np.asarray(ids, np.int64)
    values = np.asarray(values)
    if ids.ndim != 1 or len(ids) != len(values):
        raise ValueError("ids must be 1-D and match values length")
    if values.shape != (len(ids), spec.dim):
        raise ValueError(
            f"values shape {values.shape} != ({len(ids)}, {spec.dim}) "
            f"for table {name!r}"
        )
    if len(ids) and (ids.min() < 0 or ids.max() >= spec.num_ids):
        raise ValueError(f"ids out of range for table {name!r} ({spec.num_ids})")
    rps = rows_per_shard(spec.num_ids, store.num_shards)
    phys = np.asarray(id_to_phys(ids, store.num_shards, rps))
    table = store.tables[name]
    dtype = table.dtype
    # Host-side row overwrite, then place back sharded. Loads are rare,
    # host-bandwidth-bound events; keeping them out of jit avoids both
    # per-call recompiles and baking multi-hundred-MB tables into XLA
    # programs as constants.
    if len(ids) == spec.num_ids and len(np.unique(ids)) == spec.num_ids:
        # Full overwrite: every real row is supplied, so skip downloading
        # the about-to-be-discarded table; padding rows (never addressed by
        # any valid id) are zero-filled.
        host = np.zeros(table.shape, dtype)
        host[phys] = values.astype(dtype)
    else:
        host = store._host_table(name).astype(dtype, copy=True)
        host[phys] = values.astype(dtype)
    if store.sharding.is_fully_addressable:
        store.tables[name] = jax.device_put(host, store.sharding)
    else:
        # Multi-controller: materialize only this process's shards — no
        # cross-process equality collective on the full host table.
        store.tables[name] = jax.make_array_from_callback(
            host.shape, store.sharding, lambda idx: host[idx]
        )
    # A live hot replica (two-tier storage) of this table is now stale —
    # drop it; the next run entry re-splits from the rewritten canonical
    # table.
    from fps_tpu.core.store import hot_key

    store.tables.pop(hot_key(name), None)


def load_model(
    store: ParamStore,
    model: Mapping[str, np.ndarray] | str,
    *,
    strict: bool = False,
) -> None:
    """Warm-start all tables of ``store`` from a saved model.

    ``model`` is a path produced by :func:`export_model` or a dict
    ``{table_name: (num_ids, dim) array}``. Tables absent from the model keep
    their fresh initialization (``strict=True`` raises instead).
    """
    if isinstance(model, str):
        model = load_saved_model(model)
    for name, spec in store.specs.items():
        if name not in model:
            if strict:
                raise ValueError(f"model has no table {name!r}")
            continue
        values = np.asarray(model[name])
        if values.shape != (spec.num_ids, spec.dim):
            raise ValueError(
                f"table {name!r}: saved shape {values.shape} != "
                f"({spec.num_ids}, {spec.dim})"
            )
        load_rows(store, name, np.arange(spec.num_ids), values)


# ---------------------------------------------------------------------------
# Delta publications (ISSUE 14): crash-safe incremental snapshot chains.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DeltaPolicy:
    """Knobs for delta-snapshot chains (``Checkpointer(delta=...)``).

    With a policy attached, a save whose state can be described as a
    row-sparse diff against the previous publication writes a DELTA
    (``delta_{step}_{base}.npz``: per-key touched-row ids + values, each
    entry CRC-tagged like a full's, carrying ``meta::base_step`` and the
    fencing epoch) instead of rewriting whole tables — publish bytes and
    write→servable lag become O(touched rows), not O(table).

    * ``full_every`` — hard chain-length bound: at most ``full_every-1``
      consecutive deltas before the writer publishes a fresh full
      (bounds recovery-walk depth and blast radius; ``<= 1`` disables
      deltas entirely).
    * ``compact_every`` — LSM-style compaction trigger: when the live
      on-disk chain carries at least this many deltas, the next publish
      folds the chain into a fresh full at the chain head (on the
      AsyncCheckpointer this runs on the background writer thread) and
      sweeps the folded links. ``0`` = compaction only via an explicit
      :meth:`Checkpointer.compact` call.

    Touched-row sourcing: per-table supersets handed to ``save(...,
    touched_rows=...)`` (the drivers accumulate them from the PR-8/10
    traffic stream, ``WorkerLogic.pulled_ids_host``) make the diff
    O(touched); tables without a supplied set fall back to an exact
    vectorized row compare against the retained base (O(table) compute,
    still O(changed) bytes). Worker-local state (``ls::``) and hot-fold
    state (``fold::``) always use the exact compare. Either way a delta
    restores bit-identically to the full it stands in for.
    """

    full_every: int = 8
    compact_every: int = 0


class OrphanDeltaError(RuntimeError):
    """A planned delta's base publication never landed (its write
    failed or was degraded): publishing the delta would leave a broken
    chain head on disk, so the writer refuses it. Under the async
    writer's degraded mode this skips like any other degraded publish —
    the chain plan resets and the next save publishes a full."""


class TouchedRowsTracker:
    """Accumulates per-table touched-row id supersets between
    publications (driver-side source for ``save(touched_rows=...)``).

    Append-only log of per-chunk observations; :meth:`capture` unions
    the current prefix WITHOUT consuming it (a deferred/overlapped save
    may be re-captured after a quarantine recompute), and
    :meth:`commit` drops the prefix once its publication was actually
    accepted. ``observe(None)`` (an uncertifiable chunk) poisons every
    table in the prefix — those tables publish via the exact-diff
    fallback instead.
    """

    def __init__(self, tables):
        self.tables = tuple(sorted(tables))
        self._log: list = []  # per-chunk: dict[name -> ids] | None

    def observe(self, ids_by_table) -> None:
        if ids_by_table is None:
            self._log.append(None)
            return
        self._log.append({
            name: np.unique(np.asarray(ids, np.int64).reshape(-1))
            for name, ids in ids_by_table.items()})

    def capture(self) -> tuple[dict, int]:
        """``(touched_rows, marker)`` over the current prefix — tables
        unseen by every observation (or covered by an uncertifiable
        chunk) map to ``None`` (exact-diff fallback)."""
        marker = len(self._log)
        prefix = self._log[:marker]
        unknown = any(obs is None for obs in prefix)
        out = {}
        for name in self.tables:
            if unknown or any(name not in obs for obs in prefix):
                out[name] = None
                continue
            parts = [obs[name] for obs in prefix]
            out[name] = (np.unique(np.concatenate(parts)) if parts
                         else np.zeros(0, np.int64))
        return out, marker

    def commit(self, marker: int) -> None:
        del self._log[:marker]


# ---------------------------------------------------------------------------
# Periodic checkpointing (tables + worker-local state + step counter).
# ---------------------------------------------------------------------------

class Checkpointer:
    """Snapshot/restore the full training state under a directory.

    Layout: ``{dir}/ckpt_{step:012d}.npz`` holding every table (logical
    order) plus the flattened ``local_state`` pytree. ``keep`` bounds how
    many snapshots are retained.

    Restore re-lays-out *tables* onto the current mesh, so a checkpoint taken
    on one shard count resumes on another (the reference could not even
    save). Worker-local state saved through the Trainer path is stored in
    the logic's worker-count-independent export form (e.g. MF user factors
    in logical user order) — ``Trainer.restore_checkpoint`` re-lays it out
    for any worker count when the logic implements ``import_local_state``;
    the raw :meth:`restore` keeps the same-worker-count contract.

    Integrity: every array is saved with a ``meta::crc::<key>`` CRC-32
    tag, verified by :meth:`read_snapshot` (so by both restore paths).
    When the latest snapshot turns out truncated/bit-flipped, an
    auto-resolved restore (``step=None``) logs, renames the bad file to
    ``*.corrupt``, and falls back to the previous surviving snapshot —
    ``keep >= 2`` is therefore a real redundancy contract, not just a
    disk-usage knob. Pinning an explicit ``step=`` raises
    :class:`~fps_tpu.core.resilience.SnapshotCorruptionError` instead.
    Construction sweeps stale ``*.tmp.npz`` files (leftovers of a save
    that died mid-write before its atomic rename) — but only ones older
    than :attr:`TMP_SWEEP_AGE_S`, so a concurrent writer's in-flight tmp
    file is never deleted from under it.

    Delta chains (``delta=DeltaPolicy(...)``, ISSUE 14): saves publish
    row-sparse DELTAS against the previous publication when that is
    smaller — publish bytes become O(touched rows) — with recovery
    walking full→delta chains (a torn/CRC-failing/epoch-stale link
    truncates back to the last verified one, and quarantining a full
    quarantines every delta chained on it) and :meth:`compact` folding
    chains back into fulls LSM-style under the same atomic-rename +
    fence-precommit discipline. ``docs/resilience.md`` has the failure
    model; ``docs/serving.md`` the read-side contract.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 fence_epoch: int | None = None,
                 delta: DeltaPolicy | None = None,
                 retry: _retry.RetryPolicy | None = None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.dir = directory
        self.keep = keep
        # Hostile-filesystem survival (fps_tpu.core.retry): every publish
        # retries transient I/O errors (ENOSPC/EIO/ETIMEDOUT/...) under a
        # bounded, deterministically-jittered backoff before failing —
        # seeded per directory so co-located writers desynchronize.
        # RetryPolicy(retries=0) disables retries entirely.
        self.retry_policy = (retry if retry is not None
                             else dataclasses.replace(
                                 _retry.DEFAULT_PUBLISH_RETRY,
                                 seed=directory))
        # Degraded-mode accounting (the AsyncCheckpointer skips a
        # publish after retries instead of crashing training; the sync
        # base class raises, so these stay 0 here).
        self.degraded_publishes = 0
        self._publish_backlog = 0
        # Pod fencing epoch (fps_tpu.supervise.pod): checked against the
        # directory's ``pod_fence.json`` immediately before every
        # publish. ``None`` = this writer predates/ignores the pod
        # contract — it may publish into an UNfenced dir, but a fenced
        # dir refuses it too (a stale pre-abort child must never leak a
        # checkpoint into the pod's new attempt). Children read their
        # epoch from the pod env contract: ``fence_epoch_from_env()``.
        self.fence_epoch = fence_epoch
        # Delta-snapshot chains (DeltaPolicy): _chain_base retains the
        # last publication's full-form host arrays (one snapshot's worth
        # of host memory — the same order the async writer's queue slot
        # already costs) so a save can be planned as a row-sparse diff;
        # _chain_head/_chain_len track the live chain. All three are
        # advisory plan state: the ON-DISK chain is the source of truth
        # and a restart re-derives them from read_snapshot.
        self.delta_policy = delta
        self._chain_base: dict | None = None
        self._chain_head: int | None = None
        self._chain_len = 0
        # Publication accounting (bench / chaos evidence; the writer
        # thread is the single mutator under the async subclass).
        self.full_publishes = 0
        self.delta_publishes = 0
        self.compactions = 0
        self.publish_bytes_total = 0
        self.delta_bytes_total = 0
        # Test seam for the compaction chaos scenarios: called with a
        # phase name ("precommit" — after the new full's fsync, before
        # its publishing rename; "published" — after the rename, before
        # the sweep; "swept_one" — after the first folded link is
        # removed). A chaos victim SIGKILLs itself here to pin the
        # recovery contract at every phase. None in production.
        self._compact_phase_hook = None
        os.makedirs(directory, exist_ok=True)
        self._sweep_tmp()
        self._sweep_corrupt()

    # A tmp file younger than this is treated as a LIVE write in progress
    # (another process mid-_atomic_savez) and left alone; older ones are
    # crash leftovers. Far above any realistic serialize+fsync time.
    TMP_SWEEP_AGE_S = 3600.0

    # Quarantined ``*.corrupt`` files are forensic evidence, not live
    # state — bound them (age + count, mirroring the tmp sweep) so a
    # long-lived training dir with recurring disk faults doesn't
    # accumulate dead snapshots forever: at most CORRUPT_KEEP files, none
    # older than CORRUPT_SWEEP_AGE_S.
    CORRUPT_KEEP = 4
    CORRUPT_SWEEP_AGE_S = 7 * 24 * 3600.0

    def _sweep_corrupt(self) -> None:
        """Bound the ``*.corrupt`` quarantine: drop files older than
        :attr:`CORRUPT_SWEEP_AGE_S`, and everything beyond the newest
        :attr:`CORRUPT_KEEP` even when young (a fast corruption loop must
        not fill the disk). Runs at construction and after each
        quarantine."""
        entries = []
        for f in os.listdir(self.dir):
            if not f.endswith(".corrupt"):
                continue
            path = os.path.join(self.dir, f)
            try:
                entries.append((os.path.getmtime(path), path))
            except OSError:
                continue
        entries.sort(reverse=True)  # newest first
        now = time.time()
        for rank, (mtime, path) in enumerate(entries):
            if rank < self.CORRUPT_KEEP and now - mtime < self.CORRUPT_SWEEP_AGE_S:
                continue
            try:
                _log.warning("sweeping quarantined snapshot %s",
                             os.path.basename(path))
                os.remove(path)
            except OSError:
                pass

    def _sweep_tmp(self) -> None:
        """Remove partial ``.tmp.npz`` files left by a crash mid-save.

        ``_atomic_savez`` names tmp files uniquely (mkstemp) and publishes
        only via ``os.replace``, so anything still wearing the tmp suffix
        was never a live snapshot — but it may be a CONCURRENT writer's
        in-flight file (a monitoring process constructing a Checkpointer
        on a live training dir), so only files older than
        :attr:`TMP_SWEEP_AGE_S` are swept."""
        now = time.time()
        for f in os.listdir(self.dir):
            if not f.endswith(".tmp.npz"):
                continue
            path = os.path.join(self.dir, f)
            try:
                if now - os.path.getmtime(path) < self.TMP_SWEEP_AGE_S:
                    continue
                _log.warning("sweeping stale checkpoint tmp file %s", f)
                os.remove(path)
            except OSError:
                pass

    def _path(self, step: int) -> str:
        return os.path.join(self.dir, SNAPSHOT_FMT.format(step=step))

    def _collect(self, store: ParamStore, local_state: Pytree,
                 local_state_format: str) -> dict[str, np.ndarray]:
        """Snapshot-point capture: every table + local-state leaf as HOST
        arrays (the device→host dump, with its collectives in
        multi-controller runs) — the part of a save that must happen
        synchronously at the training step it describes. Serialization
        (:meth:`_write`) can then run later/elsewhere."""
        arrays = _table_arrays(store)
        # Hot-fold optimizer state (ServerLogic.hot_fold): separate
        # ``fold::`` entries, never part of the canonical table bytes —
        # an untiered (or older) reader skips the kind, a resuming
        # tiered trainer restores it for bit-identical replay.
        from fps_tpu.core.store import FOLD_KEY_SUFFIX

        for key in sorted(store.tables):
            if not key.endswith(FOLD_KEY_SUFFIX):
                continue
            name = key[: -len(FOLD_KEY_SUFFIX)]
            arr = store.tables[key]
            if (hasattr(arr, "sharding")
                    and not arr.sharding.is_fully_addressable):
                from fps_tpu.parallel.mesh import replicate_to_mesh

                arr = replicate_to_mesh(arr, store.mesh)
            arrays[snapshot_format.FOLD_PREFIX + name] = np.asarray(arr)
        leaves, treedef = jax.tree.flatten(local_state)
        for i, leaf in enumerate(leaves):
            # Multi-controller: a worker-sharded leaf spans processes, and
            # np.asarray on a non-addressable array raises. Replicate it
            # through the same jitted-identity collective the table dump
            # uses (so save keeps the every-process-calls contract).
            if (hasattr(leaf, "sharding")
                    and not leaf.sharding.is_fully_addressable):
                from fps_tpu.parallel.mesh import replicate_to_mesh

                leaf = replicate_to_mesh(leaf, store.mesh)
            arrays[f"ls{_SEP}{i}"] = np.asarray(leaf)
        arrays[f"meta{_SEP}ls_format"] = np.array(local_state_format)
        # Mesh-shape stamp: restore onto a DIFFERENT shape takes (and
        # asserts) the explicit elastic re-split path — the invariant the
        # pod's W±1 re-planning stands on.
        arrays[snapshot_format.MESH_SHAPE_KEY] = np.array(json.dumps(
            {k: int(v) for k, v in store.mesh.shape.items()},
            sort_keys=True))
        if self.fence_epoch is not None:
            # Forensic epoch stamp: pod chaos scenarios scan these to
            # prove no stale-epoch publish ever landed behind a fence.
            arrays[snapshot_format.POD_EPOCH_KEY] = np.int64(
                self.fence_epoch)
        del treedef  # structure is supplied by local_state_like at restore
        return arrays

    def _check_fence(self, step: int) -> None:
        """Refuse to publish behind a pod fence. Read FRESH on every
        write (never cached): the fence appears asynchronously, dropped
        by the pod leader into this directory when a newer attempt is
        commanded — from that point this writer is a zombie of an aborted
        attempt and must fail loudly, not land a stale snapshot."""
        from fps_tpu.supervise import child as _pod

        ok, min_epoch = _pod.fence_allows(self.dir, self.fence_epoch)
        if ok:
            return
        _obs_event("checkpoint_fenced", step=int(step),
                   epoch=self.fence_epoch, min_epoch=min_epoch,
                   dir=self.dir)
        _obs_metric("inc", "checkpoint.fenced_publishes", 1)
        raise _pod.StaleEpochError(
            f"checkpoint step {step} refused: writer epoch "
            f"{self.fence_epoch} is behind the pod fence (min_epoch "
            f"{min_epoch}) in {self.dir} — this process belongs to an "
            "attempt the pod has aborted and restarted past"
        )

    def _write(self, step: int, arrays: dict[str, np.ndarray], *,
               base: int | None = None) -> str:
        """Serialize half of a save: CRC tags, atomic fsync'd write,
        telemetry, retention GC. Runs on the caller's thread here; the
        AsyncCheckpointer runs it on its writer thread. ``base`` is not
        None for a DELTA publication (``arrays`` already holds the
        sparse entries from :meth:`_plan_publication`)."""
        self._check_fence(step)
        if base is not None and base not in self._pubs():
            # The async writer may reach this delta AFTER its base's
            # write failed (the plan ran on the caller thread while the
            # base was still in flight): publishing it would leave a
            # broken chain head on disk. Refuse — the caller sees the
            # error (and the base's original failure) on its next
            # save/flush, and the chain plan resets to a full.
            raise OrphanDeltaError(
                f"refusing orphan delta step {step}: base publication "
                f"{base} never landed under {self.dir}")
        arrays = dict(arrays)
        for k in list(arrays):
            arrays[_CRC_PREFIX + k] = np.uint32(array_crc32(arrays[k]))
        path = (self._path(step) if base is None
                else snapshot_format.delta_path(self.dir, step, base))
        t0 = time.perf_counter()
        # The fence is re-checked as the PRE-COMMIT hook, after the slow
        # serialize+fsync and immediately before the publishing rename —
        # a fence that lands while a big table is serializing still wins.
        # Every link of a delta chain re-reads it the same way: a stale
        # zombie can no more extend a chain than publish a full.
        self._savez_with_retry(path, arrays,
                               precommit=lambda: self._check_fence(step))
        secs = time.perf_counter() - t0
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = -1
        # "publication", not "kind": the record envelope already uses
        # the "kind" key for event-vs-metric.
        _obs_event("checkpoint_saved", step=int(step), path=path,
                   seconds=round(secs, 4), bytes=nbytes,
                   publication="full" if base is None else "delta",
                   **({} if base is None else {"base": int(base)}))
        _obs_metric("inc", "checkpoint.saves", 1)
        _obs_metric("observe", "checkpoint.save_seconds", secs)
        if nbytes >= 0:
            if base is None:
                # FULLS only: this gauge is the payload-proportionality
                # reference checkpoint.delta_bytes is compared against —
                # letting a small delta overwrite it would make the
                # obs_report ratio meaningless.
                _obs_metric("set", "checkpoint.bytes", nbytes)
            self.publish_bytes_total += nbytes
        if base is None:
            self.full_publishes += 1
        else:
            self.delta_publishes += 1
            if nbytes >= 0:
                self.delta_bytes_total += nbytes
                _obs_metric("inc", "checkpoint.delta_bytes", nbytes)
            _obs_metric("inc", "checkpoint.delta_publishes", 1)
        self._gc()
        self._maybe_auto_compact()
        return path

    def _savez_with_retry(self, path: str, arrays, *, precommit=None
                          ) -> None:
        """``_atomic_savez`` under this writer's :class:`RetryPolicy`:
        transient I/O failures (errno-classified by
        ``fps_tpu.core.retry``) retry with bounded deterministic
        backoff; a fence refusal in ``precommit`` is fatal and raises
        through immediately (a zombie must never keep hammering the
        directory). Each retry leaves no partial state: a failed
        attempt's tmp file is removed by ``_atomic_savez`` itself."""

        def on_retry(attempt, err, delay):
            _log.warning(
                "transient I/O failure publishing %s (attempt %d, "
                "retrying in %.3fs): %r", os.path.basename(path),
                attempt + 1, delay, err)
            _obs_metric("inc", "storage.retries", 1, plane="checkpoint")

        _retry.call_with_retry(
            lambda: _atomic_savez(path, arrays, precommit=precommit),
            policy=self.retry_policy, op="publish", on_retry=on_retry)

    def save(self, step: int, store: ParamStore, local_state: Pytree = None,
             *, local_state_format: str = "raw",
             touched_rows: Mapping | None = None) -> str:
        """``local_state_format`` tags how the local-state leaves are laid
        out: ``"raw"`` (device layout, restorable via :meth:`restore` at
        the same worker count) or ``"exported"`` (the worker logic's
        worker-count-independent form, written by the Trainer path and
        restorable only via ``Trainer.restore_checkpoint``). The tag makes
        a mismatched restore fail loudly instead of silently permuting
        state when shapes happen to coincide.

        ``touched_rows`` (delta chains only): per-table id SUPERSETS of
        the rows touched since the last publication (``None`` entries /
        a ``None`` dict fall back to the exact row compare). Ignored
        without a :class:`DeltaPolicy`."""
        arrays = self._collect_timed(store, local_state, local_state_format)
        step, base, payload = self._plan_publication(
            int(step), arrays, touched_rows)
        try:
            return self._write(step, payload, base=base)
        except BaseException:
            # The planned chain state described a publication that never
            # landed — a later delta must not chain onto it.
            self._chain_reset()
            raise

    # -- delta-chain planning (caller thread, serial) ----------------------

    def _chain_reset(self) -> None:
        self._chain_base = None
        self._chain_head = None
        self._chain_len = 0

    def _plan_publication(self, step: int, arrays: dict,
                          touched_rows: Mapping | None
                          ) -> tuple[int, int | None, dict]:
        """Decide full vs delta for one save: returns ``(step, base,
        payload)`` (``base is None`` = full, payload = the entries to
        serialize) and advances the in-memory chain plan. Exactness
        rule: a delta is only planned when EVERY entry of the new state
        is either bit-carried from the retained base or explicitly in
        the payload — anything surprising (no policy, no base, key/shape
        drift, non-monotone step, chain at its length bound, delta not
        actually smaller) publishes a full."""
        policy = self.delta_policy
        if policy is None or policy.full_every <= 1:
            return step, None, arrays
        # The retained base must OWN its memory: a zero-copy view of a
        # device buffer the next step donates away would silently rot
        # the diff baseline (the async writer makes the same copy for
        # its queue slot; here it protects the sync path too).
        arrays = dict(arrays)
        for k, v in arrays.items():
            if isinstance(v, np.ndarray) and not v.flags["OWNDATA"]:
                arrays[k] = np.array(v, copy=True)
        base_ok = (self._chain_base is not None
                   and self._chain_head is not None
                   and step > self._chain_head
                   and self._chain_len + 1 < policy.full_every)
        payload = (self._delta_entries(arrays, touched_rows)
                   if base_ok else None)
        if payload is not None:
            full_bytes = sum(getattr(v, "nbytes", 0)
                             for v in arrays.values())
            delta_bytes = sum(getattr(v, "nbytes", 0)
                              for v in payload.values())
            if delta_bytes >= full_bytes:
                payload = None  # no savings: a full is strictly better
        if payload is None:
            self._chain_base = dict(arrays)
            self._chain_head = step
            self._chain_len = 0
            return step, None, arrays
        base = self._chain_head
        payload[snapshot_format.BASE_STEP_KEY] = np.int64(base)
        # Advance the retained base to the state this delta describes
        # (overlay by reference: the arrays are fresh host buffers).
        new_base = dict(self._chain_base)
        for k, v in arrays.items():
            new_base[k] = v
        self._chain_base = new_base
        self._chain_head = step
        self._chain_len += 1
        return step, base, payload

    def _delta_entries(self, arrays: dict, touched_rows: Mapping | None
                       ) -> dict | None:
        """Row-sparse diff of ``arrays`` against the retained chain base:
        ``dids::K``/``drows::K`` pairs for row-sparse keys, plain-key
        full replacements for everything else that changed, nothing for
        bit-identical entries. ``None`` when the structural contract
        broke (key set / shape / dtype drift on a row-sparse kind)."""
        base = self._chain_base
        fmt = snapshot_format
        sparse_kinds = (f"table{_SEP}", fmt.FOLD_PREFIX, f"ls{_SEP}")
        out: dict[str, np.ndarray] = {}
        for k, v in arrays.items():
            if k.startswith(f"meta{_SEP}"):
                # Meta tags ride every link in full (tiny, and the
                # chain verifier needs each delta's OWN fencing epoch —
                # an omitted-because-unchanged epoch would blind the
                # read-side staleness check).
                out[k] = v
                continue
            bv = base.get(k)
            row_sparse = (k.startswith(sparse_kinds)
                          and getattr(v, "ndim", 0) >= 2)
            if bv is None:
                if row_sparse:
                    return None  # a new table/leaf appeared: full
                out[k] = v
                continue
            same_layout = (getattr(bv, "shape", None) == v.shape
                           and getattr(bv, "dtype", None) == v.dtype)
            if not same_layout:
                if row_sparse:
                    return None
                out[k] = v
                continue
            if not row_sparse:
                if not np.array_equal(bv, v):
                    out[k] = v
                continue
            ids = None
            if touched_rows is not None and k.startswith(f"table{_SEP}"):
                ids = touched_rows.get(k.split(_SEP, 1)[1])
            if ids is not None:
                # Tracker-sourced superset: O(touched) work, no compare.
                ids = np.unique(np.asarray(ids, np.int64).reshape(-1))
                ids = ids[(ids >= 0) & (ids < len(v))]
            else:
                # Exact vectorized row compare against the base.
                tail = tuple(range(1, v.ndim))
                neq = (v != bv)
                ids = np.flatnonzero(np.any(neq, axis=tail)
                                     if tail else neq)
            out[fmt.DELTA_IDS_PREFIX + k] = np.asarray(ids, np.int64)
            out[fmt.DELTA_ROWS_PREFIX + k] = np.ascontiguousarray(v[ids])
        # Row-sparse keys present in the base but dropped from the new
        # state (a model-definition change): structural — publish full.
        for k in base:
            if (k.startswith(sparse_kinds) and k not in arrays
                    and not k.startswith(_CRC_PREFIX)):
                return None
        return out

    def _capture_timed(self, store, local_state, local_state_format):
        """:meth:`_collect` plus the ``checkpoint.capture_seconds``
        metric — the device→host capture cost wherever it runs (caller
        thread here; the AsyncCheckpointer's deferred path runs it on
        the writer thread, where it overlaps device compute instead of
        stalling dispatch)."""
        t0 = time.perf_counter()
        arrays = self._collect(store, local_state, local_state_format)
        _obs_metric("observe", "checkpoint.capture_seconds",
                    time.perf_counter() - t0)
        return arrays

    def _collect_timed(self, store, local_state, local_state_format):
        """:meth:`_capture_timed` plus the ``checkpoint.dump_seconds``
        metric — what a save costs the TRAINING thread. On this inline
        path the two series coincide (the caller pays the capture); a
        deferred capture records dump_seconds around the enqueue only,
        so the split attributes any residual stall."""
        t0 = time.perf_counter()
        arrays = self._capture_timed(store, local_state, local_state_format)
        _obs_metric("observe", "checkpoint.dump_seconds",
                    time.perf_counter() - t0)
        return arrays

    def flush(self) -> None:
        """Durability barrier — every accepted :meth:`save` is on disk
        when this returns. The synchronous base class already is; the
        :class:`AsyncCheckpointer` override waits for its writer."""

    def close(self) -> None:
        """Release writer resources (no-op here; see
        :class:`AsyncCheckpointer`). Safe to call twice."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def steps(self) -> list[int]:
        """Published steps, ascending — every publication counts: fulls
        AND delta links (a delta step restores via its chain)."""
        return sorted(self._pubs())

    def _pubs(self) -> dict:
        """Live publication index ({step: Publication}) — re-scanned per
        call; the directory is the source of truth (concurrent writers,
        compaction, quarantine all mutate it)."""
        return snapshot_format.publications(self.dir)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def _resolve_step(self, step: int | None) -> int:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.dir}")
        return step

    def _read_entries(self, step: int, path: str, verify: bool) -> dict:
        """Load every non-CRC entry of ONE publication file, verifying
        each against its ``meta::crc`` tag. Raises
        :class:`SnapshotCorruptionError` carrying ``.step`` (the failing
        link — chain reads truncate back to the last verified one)."""
        try:
            path = _retry.read_path(path)  # stale read-after-rename seam
            with np.load(path) as z:
                entries = {k: z[k] for k in z.files
                           if not k.startswith(_CRC_PREFIX)}
                if verify:
                    for k, v in entries.items():
                        ck = _CRC_PREFIX + k
                        if ck in z.files and int(z[ck]) != array_crc32(v):
                            err = SnapshotCorruptionError(
                                f"snapshot step {step}: checksum mismatch "
                                f"on entry {k!r}"
                            )
                            err.step = step
                            raise err
        except (SnapshotCorruptionError, FileNotFoundError):
            # A missing file is "no such checkpoint", not disk corruption —
            # a pinned-but-gc'd step must keep raising FileNotFoundError.
            raise
        except _IO_ERRORS as e:
            err = SnapshotCorruptionError(
                f"snapshot step {step} unreadable: {e!r}"
            )
            err.step = step
            raise err from e
        return entries

    def _resolve_entries(self, step: int, verify: bool) -> dict:
        """Full-form entries of publication ``step`` — a full reads one
        file; a delta walks its chain (every link verified) and overlays
        base→head. A broken/stale/corrupt link raises
        :class:`SnapshotCorruptionError` with ``.step`` naming the LINK,
        so the auto-resolve fallback quarantines exactly the failing
        suffix and truncates the chain back to the last verified one."""
        pubs = self._pubs()
        pub = pubs.get(step)
        if pub is None:
            # Historical contract: a never-published step reads as "no
            # such checkpoint" from the single-file open.
            return self._read_entries(step, self._path(step), verify)
        if pub.kind == "full":
            return self._read_entries(step, pub.path, verify)
        try:
            members = snapshot_format.chain_members(pubs, step)
        except snapshot_format.ChainError as e:
            err = SnapshotCorruptionError(str(e))
            err.step = e.step if e.step is not None else step
            raise err from e
        ok, reason, failing = snapshot_format._check_chain_meta(members)
        if not ok:
            err = SnapshotCorruptionError(
                f"delta chain for step {step} refused: {reason}")
            err.step = failing if failing is not None else step
            raise err
        entries = self._read_entries(members[0].step, members[0].path,
                                     verify)
        for link in members[1:]:
            delta = self._read_entries(link.step, link.path, verify)
            try:
                entries = snapshot_format.apply_delta_entries(
                    entries, delta)
            except snapshot_format.ChainError as e:
                err = SnapshotCorruptionError(
                    f"delta step {link.step} does not apply: {e}")
                err.step = link.step
                raise err from e
        entries.pop(snapshot_format.BASE_STEP_KEY, None)
        return entries

    def _read_verified(self, step: int, verify: bool, *,
                       anchor: bool = False) -> tuple[dict, list, str]:
        """Load EVERY entry of one publication (chain-resolved for
        deltas), checking each against its ``meta::crc`` tag; any read
        error, checksum mismatch, or broken chain raises
        :class:`SnapshotCorruptionError`. Pre-integrity snapshots (no crc
        tags) still get the structural checks — an unreadable zip fails
        either way.

        ``anchor=True`` (the RESTORE path only — ``read_snapshot``)
        re-anchors the delta chain plan on the resolved state so the
        next save may chain from it. Verification reads
        (``verify_snapshot`` / ``latest_valid_step``) must NOT anchor:
        resetting the plan's length on every monitoring probe would
        defeat the ``full_every`` chain-depth bound."""
        entries = self._resolve_entries(step, verify)
        if anchor and self.delta_policy is not None:
            self._chain_base = dict(entries)
            self._chain_head = step
            # Plan length = the resolved publication's ACTUAL on-disk
            # chain depth, so full_every bounds total recovery-walk
            # depth across restarts, not just deltas-since-restore.
            try:
                self._chain_len = sum(
                    1 for p in snapshot_format.chain_members(
                        self._pubs(), step) if p.kind == "delta")
            except snapshot_format.ChainError:
                self._chain_len = 0
        tables = {
            k.split(_SEP, 1)[1]: v
            for k, v in entries.items()
            if k.startswith(f"table{_SEP}")
        }
        # Hot-fold state rides the same values dict under its full
        # ``fold::<name>`` key (table names never contain the separator,
        # so the kinds cannot collide); load_tables re-installs it. The
        # mesh-shape stamp rides along the same way so load_tables can
        # detect (and assert) an elastic re-split restore.
        tables.update({
            k: v for k, v in entries.items()
            if k.startswith(snapshot_format.FOLD_PREFIX)
        })
        if snapshot_format.MESH_SHAPE_KEY in entries:
            tables[snapshot_format.MESH_SHAPE_KEY] = entries[
                snapshot_format.MESH_SHAPE_KEY]
        return tables, _ls_leaves(entries), _ls_format(entries)

    def _quarantine(self, step: int, err: Exception) -> None:
        """Take a corrupt publication out of the rotation (rename to
        ``*.corrupt`` — preserved for forensics, invisible to
        :meth:`steps`) — AND every delta chained on it, transitively: a
        descendant's state is defined in terms of the quarantined link,
        so no reader may ever resolve a chain through it."""
        pubs = self._pubs()
        pub = pubs.get(step)
        path = pub.path if pub is not None else self._path(step)
        _log.warning(
            "discarding corrupt snapshot step %d (%s); falling back to the "
            "previous surviving snapshot", step, err,
        )
        _obs_event("checkpoint_fallback", step=int(step), path=path,
                   error=repr(err))
        _obs_metric("inc", "checkpoint.fallbacks", 1)
        bad = {step}
        doomed = [path]
        # Transitive descendants: any delta whose back-chain passes
        # through a quarantined step.
        changed = True
        while changed:
            changed = False
            for s, p in pubs.items():
                if s not in bad and p.kind == "delta" and p.base in bad:
                    bad.add(s)
                    doomed.append(p.path)
                    changed = True
        for i, p in enumerate(doomed):
            if i:  # the failing link was already logged/evented above
                _log.warning(
                    "quarantining %s: chained on corrupt step %d",
                    os.path.basename(p), step)
                _obs_event("checkpoint_fallback", path=p,
                           step=int(step), chained=True,
                           error="chained on quarantined step")
            try:
                os.replace(p, p + ".corrupt")
                # Age from NOW: the rename preserves the snapshot's
                # original mtime, and an old-enough snapshot would
                # otherwise be deleted by the very sweep below — the
                # sweep's age bound is about time-in-quarantine, not
                # snapshot age.
                os.utime(p + ".corrupt")
            except OSError:
                pass
        self._sweep_corrupt()  # keep the quarantine bounded (age + count)

    def read_snapshot(
        self, step: int | None = None, *, verify: bool = True
    ) -> tuple[int, dict, list, str]:
        """ONE-open read of a snapshot: ``(step, {table: values},
        local_state_leaves, local_state_format)``. The other accessors and
        both restore paths are built on this so a restore parses the .npz
        exactly once.

        Integrity contract: every entry is CRC-verified (``verify=False``
        opts out). With ``step=None`` a corrupt snapshot is quarantined
        and the read falls back to the previous surviving one; with an
        explicit ``step`` corruption raises
        :class:`SnapshotCorruptionError` (the caller pinned that exact
        snapshot, silently answering with another would lie)."""
        explicit = step is not None
        step = self._resolve_step(step)
        tried: set[int] = set()
        reread: set[int] = set()
        while True:
            try:
                tables, leaves, fmt = self._read_verified(step, verify,
                                                          anchor=True)
                return step, tables, leaves, fmt
            except FileNotFoundError:
                if explicit:
                    raise
                # Transient ENOENT / sweep race: a listed file is gone
                # or invisible on THIS read (stale mount, a compaction
                # sweep between list and open). Retry the step once —
                # the stale-mount case recovers — then fall back to
                # older survivors WITHOUT quarantining: there is
                # nothing on disk to quarantine, and the brownout
                # contract says a read hiccup must not crash a restore
                # that has intact older snapshots.
                if step not in reread:
                    reread.add(step)
                    continue
                tried.add(step)
                candidates = [s for s in self.steps() if s not in tried]
                if not candidates:
                    raise
                step = candidates[-1]
            except SnapshotCorruptionError as err:
                if explicit:
                    raise
                bad = getattr(err, "step", step)
                # Transient-read guard (hostile filesystems): a stale
                # or flaky read can make durable, VALID bytes look
                # corrupt for one open — quarantining on that verdict
                # would destroy landed state over a read hiccup. Before
                # quarantining, re-verify the failing link on a fresh
                # read, once: clean ⇒ retry the resolve; still bad ⇒
                # real corruption, quarantine as before.
                if bad not in reread:
                    reread.add(bad)
                    pub = self._pubs().get(bad)
                    p = pub.path if pub is not None else self._path(bad)
                    ok, _ = snapshot_format.verify_snapshot_file(p)
                    if ok:
                        continue
                tried.add(step)  # terminates even if quarantine can't
                # Quarantine the FAILING link (a mid-chain delta names
                # itself via err.step) plus everything chained on it —
                # the fallback then lands on the last verified link.
                self._quarantine(bad, err)
                candidates = [s for s in self.steps() if s not in tried]
                if not candidates:
                    raise FileNotFoundError(
                        f"no intact checkpoints under {self.dir} (latest "
                        f"was corrupt: {err})"
                    ) from err
                step = candidates[-1]

    def verify_snapshot(self, step: int | None = None) -> bool:
        """Full integrity pass over one snapshot (default: latest) without
        loading it into a store: ``True`` iff every entry reads back and
        matches its recorded checksum."""
        try:
            self._read_verified(self._resolve_step(step), True)
            return True
        except (SnapshotCorruptionError, FileNotFoundError):
            return False

    def latest_valid_step(self) -> int | None:
        """Newest step whose snapshot passes :meth:`verify_snapshot`
        (scanning newest→oldest); ``None`` when none does. Read-only —
        corrupt files are left in place (restore quarantines them)."""
        for s in reversed(self.steps()):
            if self.verify_snapshot(s):
                return s
        return None

    def load_tables(self, store: ParamStore, step: int, values_by_name: dict
                    ) -> dict:
        """Validate and load pre-read table arrays (from
        :meth:`read_snapshot`) into ``store`` — public because
        ``Trainer.restore_checkpoint`` builds on it.

        Elastic re-split: when the snapshot's recorded mesh shape differs
        from the store's current mesh, this restore IS the re-split path
        the pod's W±1 re-planning depends on — tables are stored in
        logical id order, so ``load_rows`` re-lays every row onto the new
        owner-major layout. The path is taken explicitly (event + metric)
        and ASSERTED: each re-split table must round-trip bit-identically
        back to the snapshot's logical bytes."""
        saved_shape = None
        raw = values_by_name.get(snapshot_format.MESH_SHAPE_KEY)
        if raw is not None:
            try:
                saved_shape = json.loads(str(raw))
            except (TypeError, ValueError):
                saved_shape = None
        cur_shape = {k: int(v) for k, v in store.mesh.shape.items()}
        resplit = bool(saved_shape) and saved_shape != cur_shape
        if resplit:
            _log.info("checkpoint step %d: mesh-shape re-split %s -> %s",
                      step, saved_shape, cur_shape)
            _obs_event("checkpoint_resplit", step=int(step),
                       from_shape=saved_shape, to_shape=cur_shape)
            _obs_metric("inc", "checkpoint.resplits", 1)
        for name, spec in store.specs.items():
            if name not in values_by_name:
                raise ValueError(
                    f"checkpoint step {step} has no table {name!r} — "
                    "was it taken with an older model definition?"
                )
            values = values_by_name[name]
            if values.shape != (spec.num_ids, spec.dim):
                raise ValueError(
                    f"checkpoint table {name!r} shape {values.shape} != "
                    f"store spec ({spec.num_ids}, {spec.dim})"
                )
            load_rows(store, name, np.arange(len(values)), values)
        # Any live tiering aux entries (hot replicas, adaptive slot maps,
        # tracker sketches) are projections of — or windows over — the
        # state just overwritten: stale now. Drop them all so the
        # run-entry re-split (Trainer._attach_hot) derives fresh entries
        # from the restored canonical tables (and the restored tracker
        # state) instead of silently serving pre-restore values.
        from fps_tpu.core.store import FOLD_KEY_SUFFIX, is_aux_key

        for key in [k for k in store.tables if is_aux_key(k)]:
            del store.tables[key]
        # Hot-fold optimizer state is the one aux kind that is NOT a
        # projection of the canonical table — re-install the snapshot's
        # ``fold::`` arrays (sharded like the tables; _attach_hot keeps
        # them when the resolution still matches, drops them otherwise).
        for key in sorted(values_by_name):
            if not key.startswith(snapshot_format.FOLD_PREFIX):
                continue
            name = key[len(snapshot_format.FOLD_PREFIX):]
            if name not in store.specs:
                continue
            arr = np.asarray(values_by_name[key], np.float32)
            store.tables[name + FOLD_KEY_SUFFIX] = jax.device_put(
                arr, store.sharding)
        if resplit:
            # The explicit re-split assertion: every table, re-laid-out
            # onto the new mesh, dumps back to EXACTLY the snapshot's
            # logical bytes. Runs only on shape-changed restores (rare,
            # boundary events), so the extra dump is off the common path.
            for name in store.specs:
                got = store.dump_model(name)[1]
                want = np.asarray(values_by_name[name], got.dtype)
                if not np.array_equal(got, want):
                    raise AssertionError(
                        f"elastic re-split restore of table {name!r} is "
                        f"not bit-identical across mesh shapes "
                        f"{saved_shape} -> {cur_shape} at step {step} — "
                        "the flush-reconcile canonical-snapshot "
                        "invariant is broken"
                    )
        return dict(store.tables)

    def restore_tables(
        self, store: ParamStore, *, step: int | None = None
    ) -> tuple[dict, int]:
        """Load a snapshot's tables into ``store`` (sharded on its current
        mesh — any shard count). Returns ``(tables, step)``."""
        step, values, _, _ = self.read_snapshot(step)
        return self.load_tables(store, step, values), step

    def raw_local_state(self, step: int | None = None) -> list[np.ndarray]:
        """The snapshot's local-state leaves as saved (flattened order).

        Rides :meth:`read_snapshot`, so it shares the integrity contract —
        CRC verification and, for ``step=None``, fallback past a corrupt
        newest snapshot (at the price of reading the whole file)."""
        return self.read_snapshot(step)[2]

    def local_state_format(self, step: int | None = None) -> str:
        """``"raw"`` or ``"exported"`` (pre-tag snapshots read as raw).

        Rides :meth:`read_snapshot` — same integrity/fallback contract as
        :meth:`raw_local_state`."""
        return self.read_snapshot(step)[3]

    def restore(
        self,
        store: ParamStore,
        local_state_like: Pytree = None,
        *,
        step: int | None = None,
    ) -> tuple[dict, Pytree, int]:
        """Load a snapshot into ``store`` (sharded on its current mesh).

        ``local_state_like`` supplies the pytree structure and shardings to
        restore worker-local state into (pass the output of
        ``Trainer.init_state``; pass ``None`` if there is none). Local
        state is restored RAW — same worker count as the save; for
        worker-count-elastic restores of logics that support it, use
        ``Trainer.restore_checkpoint``.

        Returns ``(tables, local_state, step)``.
        """
        step, values, ls_leaves, fmt = self.read_snapshot(step)
        self.load_tables(store, step, values)
        if ls_leaves and fmt == "exported":
            raise ValueError(
                f"checkpoint step {step} stores local state in the worker "
                "logic's EXPORTED form (written by the Trainer path); "
                "restore it with Trainer.restore_checkpoint, not the raw "
                "Checkpointer.restore"
            )
        like_leaves, treedef = jax.tree.flatten(local_state_like)
        if len(like_leaves) != len(ls_leaves):
            raise ValueError(
                f"checkpoint step {step} has {len(ls_leaves)} local-state "
                f"leaves, local_state_like has {len(like_leaves)} — "
                "was save() called without local_state?"
            )
        placed = [
            jax.device_put(
                np.asarray(saved, getattr(like, "dtype", None)),
                like.sharding if isinstance(like, jax.Array) else None,
            )
            for saved, like in zip(ls_leaves, like_leaves)
        ]
        local_state = jax.tree.unflatten(treedef, placed)
        return dict(store.tables), local_state, step

    def _gc(self) -> None:
        """Retention by PATH protection: the newest ``keep`` publication
        heads plus every link their back-chains reference survive;
        everything else (superseded fulls, folded/orphaned deltas, the
        shadowed delta a compaction's full replaced) is removed. For a
        fulls-only directory this is exactly the legacy newest-``keep``
        rule. A head whose chain is BROKEN (base swept mid-crash) is
        unrestorable and therefore unprotected."""
        pubs = self._pubs()
        heads = sorted(pubs)[max(0, len(pubs) - self.keep):]
        protected: set[str] = set()
        for h in heads:
            try:
                members = snapshot_format.chain_members(pubs, h)
            except snapshot_format.ChainError:
                continue
            protected.update(p.path for p in members)
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return
        for f in names:
            if not (SNAPSHOT_RE.fullmatch(f)
                    or snapshot_format.DELTA_RE.fullmatch(f)):
                continue
            path = os.path.join(self.dir, f)
            if path in protected:
                continue
            try:
                os.remove(path)
            except OSError:
                pass

    # -- LSM-style chain compaction ----------------------------------------

    def _maybe_auto_compact(self) -> None:
        """Fold the live chain when it carries >= ``compact_every``
        deltas (DeltaPolicy). Runs where :meth:`_write` runs — the
        background writer thread under :class:`AsyncCheckpointer`, so a
        training loop never blocks on compaction."""
        policy = self.delta_policy
        if policy is None or policy.compact_every <= 0:
            return
        pubs = self._pubs()
        if not pubs:
            return
        head = max(pubs)
        if pubs[head].kind != "delta":
            return
        try:
            members = snapshot_format.chain_members(pubs, head)
        except snapshot_format.ChainError:
            return
        if sum(1 for p in members if p.kind == "delta") >= \
                policy.compact_every:
            try:
                self.compact()
            except Exception as e:
                # A fence refusal is the zombie-writer signal and must
                # propagate (the publish path treats it as fatal); any
                # other compaction failure is a deferred optimization —
                # the chain is still fully recoverable, so the SAVE that
                # triggered us must not be poisoned.
                from fps_tpu.supervise.child import StaleEpochError

                cause = e
                while cause is not None:
                    if isinstance(cause, StaleEpochError):
                        raise
                    cause = cause.__cause__
                # ENOSPC/EIO mid-fold (after the publish retry budget):
                # the fold aborts, the chain stays fully recoverable,
                # and the next publish re-triggers compaction — lost
                # optimization, never lost state (the enospc_compaction
                # chaos scenario pins this).
                _log.warning("background chain compaction failed "
                             "(chain left as-is, retried at the next "
                             "publish): %r", e)
                _obs_event("compaction_aborted", error=repr(e),
                           dir=self.dir)
                _obs_metric("inc", "storage.compaction_aborts", 1)

    def compact(self) -> str | None:
        """Fold the newest chain into a fresh FULL at its head step —
        the LSM compaction of the delta chain. Same discipline as every
        publish: serialize to a tmp file, fsync, re-read the pod fence
        as the pre-commit hook, atomic rename; then sweep the folded
        links. A SIGKILL at ANY point leaves a recoverable chain:

        * before the rename — at most a ``*.tmp.npz`` leftover, the
          chain untouched;
        * after the rename, before/mid sweep — the full and (some of)
          the folded links coexist; publication resolution prefers the
          full at the shared head step, every newer delta's ``base``
          resolves to it bit-identically (the fold IS the chain's
          resolved state), and the next GC/compaction finishes the
          sweep.

        Returns the new full's path, or None when the newest publication
        is already a full (nothing to fold). Verification failures
        surface as the usual corruption errors — compaction never folds
        an unverified link."""
        pubs = self._pubs()
        if not pubs:
            return None
        head = max(pubs)
        if pubs[head].kind != "delta":
            return None
        members = snapshot_format.chain_members(pubs, head)
        entries = self._resolve_entries(head, True)
        hook = self._compact_phase_hook

        def precommit():
            self._check_fence(head)
            if hook is not None:
                hook("precommit")

        arrays = dict(entries)
        for k in list(arrays):
            arrays[_CRC_PREFIX + k] = np.uint32(array_crc32(arrays[k]))
        path = self._path(head)
        t0 = time.perf_counter()
        self._savez_with_retry(path, arrays, precommit=precommit)
        if hook is not None:
            hook("published")
        secs = time.perf_counter() - t0
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = -1
        self.compactions += 1
        if nbytes >= 0:
            # A compaction is a real publish (an O(table) full hits the
            # disk): it must ride the same payload accounting the bench
            # ratios and the obs_report delta-vs-full comparison read.
            self.publish_bytes_total += nbytes
            _obs_metric("set", "checkpoint.bytes", nbytes)
        _obs_event("checkpoint_compacted", step=int(head), path=path,
                   folded=len(members), seconds=round(secs, 4),
                   bytes=nbytes)
        _obs_metric("inc", "checkpoint.compactions", 1)
        # Sweep the folded DELTA links (the head's delta file is now
        # shadowed by the full; the others are folded into it). The base
        # full is deliberately left to normal retention — it remains a
        # valid standalone restore point, so ``keep >= 2`` stays a real
        # redundancy contract across compactions. Read-side safety is
        # the inode contract: a reader mid-open keeps its maps.
        swept = False
        for pub in members:
            if pub.kind != "delta" or pub.path == path:
                continue
            try:
                os.remove(pub.path)
            except OSError:
                continue
            if hook is not None and not swept:
                swept = True
                hook("swept_one")
        self._gc()
        # The fold stands in for a fresh full: credit the folded deltas
        # back to the chain-length plan so the publisher keeps emitting
        # deltas instead of hitting its full_every bound against an
        # already-compacted chain (under the async writer the caller may
        # have planned newer, unfolded links meanwhile — those stay
        # counted). Advisory plan state, like the rest of the chain
        # plan: a lost race costs one early full, never correctness.
        folded = sum(1 for p in members if p.kind == "delta")
        self._chain_len = max(0, self._chain_len - folded)
        return path


class AsyncCheckpointer(Checkpointer):
    """Double-buffered background snapshot writer.

    :meth:`save` captures the snapshot point synchronously (device→host
    dump of tables + local state — the part that must see the training
    state as of ``step``) and returns; a single writer thread then does
    the expensive half — CRC tags, serialize, fsync, atomic rename — off
    the training thread. This shrinks both the per-save step-time hiccup
    (the training loop no longer blocks on serialize+fsync) and the crash
    window (the loop reaches its next step sooner).

    Contracts:

    * **double-buffered, at-most-one in-flight write** — one snapshot may
      be queued while one is being written; a third :meth:`save` blocks
      until the writer frees the slot, bounding host memory at two
      snapshots.
    * **publication is still atomic** — the writer goes through the same
      ``_atomic_savez`` tmp+fsync+rename, so a SIGKILL mid-background-
      write leaves at most a ``*.tmp.npz`` leftover, never a torn
      published snapshot, and ``latest_valid_step`` stays monotone.
    * **flush() is the durability barrier** — returns once every accepted
      save is renamed into place (the drivers call it at end of run); a
      background write failure is re-raised, once, from the next
      ``save``/``flush``/``close`` on the caller's thread.
    * **journal truth** — ``save`` emits ``checkpoint_enqueued``; the
      writer emits ``checkpoint_saved`` only after the rename, so the
      run journal's ``checkpoint_saved`` records remain TRUE durability
      points for the supervisor and ``tools/obs_report.py``.
    * the read side (:meth:`read_snapshot` and everything over it)
      flushes first, so an in-process restore always sees the newest
      accepted save. :meth:`steps` itself does NOT flush — the writer's
      own retention GC runs on the writer thread and must not deadlock.
    * **deferred capture** (:meth:`save_deferred`) — the device→host
      dump itself can move onto the writer thread behind on-device
      boundary copies: the training thread pays one enqueue
      (``checkpoint.dump_seconds``), the writer pays the capture
      (``checkpoint.capture_seconds``) overlapped with device compute.
      Delta planning rides along (queue order = save order = chain
      order), and a crash mid-capture publishes nothing — at most the
      last boundary's save is lost, exactly the inline crash window
      plus one boundary (docs/STALENESS.md).
    * **non-blocking degraded enqueue** (``when_full="degrade"``) — a
      save arriving while the slot is full (writer wedged in brownout
      retries) is skipped as a degraded publish: backlog + staleness
      SLO carry the cost, dispatch never stalls. Default stays
      ``"block"`` (lossless back-pressure).
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 fence_epoch: int | None = None,
                 delta: DeltaPolicy | None = None,
                 retry: _retry.RetryPolicy | None = None,
                 degrade: bool = True,
                 when_full: str = "block"):
        super().__init__(directory, keep=keep, fence_epoch=fence_epoch,
                         delta=delta, retry=retry)
        if when_full not in ("block", "degrade"):
            raise ValueError(
                f"when_full must be 'block' or 'degrade', got {when_full!r}")
        self._cv = threading.Condition()
        # One queue slot: ("host", step, base_step_or_None, payload) for a
        # caller-captured save, or ("deferred", step, collect, touched)
        # for a writer-side capture (save_deferred).
        self._queued: tuple | None = None
        # Deferred items enqueued but not yet chain-planned by the
        # writer: an inline save() must not plan past them (chain order
        # is save order).
        self._unplanned = 0
        self._writing = False
        self._error: BaseException | None = None
        self._closed = False
        # Degraded-mode storage (hostile-filesystem survival): with
        # ``degrade`` on, a publish that still fails TRANSIENTLY after
        # the retry budget is SKIPPED — checkpoint.publish_backlog
        # rises, storage.degraded_publishes counts, the staleness SLO
        # burns — instead of crashing training on its next save().
        # Fatal errors (EACCES/EROFS, fence refusals, corruption) keep
        # the first-error retention contract and re-raise on the caller.
        self.degrade = bool(degrade)
        # ``when_full="degrade"``: a save arriving while the queue slot
        # is still full (the writer wedged in a brownout's retry
        # backoff) is SKIPPED as a degraded publish instead of blocking
        # the training thread — one enqueue attempt, nothing more. The
        # default keeps the historical lossless back-pressure.
        self.when_full = when_full
        self._degraded_chain = False
        self._writer = threading.Thread(
            target=self._writer_loop,
            name=f"fps-ckpt-writer:{os.path.basename(directory)}",
            daemon=True,  # flush()/close() are the orderly exits; a
        )  # crashed main thread must not hang the interpreter on join
        self._writer.start()

    # -- caller side ------------------------------------------------------

    def save(self, step: int, store: ParamStore, local_state: Pytree = None,
             *, local_state_format: str = "raw",
             touched_rows: Mapping | None = None,
             when_full: str | None = None) -> str:
        arrays = self._collect_timed(store, local_state, local_state_format)
        with self._cv:
            self._raise_pending_error()
            # An inline save must not plan past a deferred item the
            # writer hasn't planned yet — chain order is save order.
            while self._unplanned and not self._closed:
                self._cv.wait()
                self._raise_pending_error()
            if self._degraded_chain:
                # A degraded (skipped) publication may be the head the
                # planner would diff against: force the next
                # publication to a FULL so no delta ever chains onto a
                # publish that never landed.
                self._chain_reset()
                self._degraded_chain = False
        # Delta planning happens HERE, serially on the caller's thread —
        # chain order is save order, and planning against the retained
        # base must see publications in that order. The enqueued payload
        # for a delta is O(touched rows): the queue slot shrinks with
        # the publish.
        step, base, payload = self._plan_publication(
            int(step), arrays, touched_rows)
        # The writer consumes these arrays on another thread while the
        # training loop runs on: every entry must OWN its memory. Dump
        # paths normally produce fresh arrays (fancy indexing), but e.g.
        # a CPU-backend jax leaf can surface as a zero-copy view of a
        # device buffer that the next step donates away.
        payload = dict(payload)
        for k, v in payload.items():
            if isinstance(v, np.ndarray) and not v.flags["OWNDATA"]:
                payload[k] = np.array(v, copy=True)
        path = (self._path(step) if base is None
                else snapshot_format.delta_path(self.dir, step, base))
        if not self._enqueue(("host", int(step), base, payload),
                             int(step), path, when_full):
            # Skipped (degraded enqueue): the planned chain state
            # described a publication that will never land.
            with self._cv:
                self._chain_reset()
        return path

    def save_deferred(self, step: int, collect, *,
                      touched_rows: Mapping | None = None,
                      when_full: str | None = None) -> str:
        """Enqueue a save whose device→host capture runs on the WRITER
        thread: ``collect()`` must return the host arrays dict a
        :meth:`_collect` call would (the driver builds it over on-device
        boundary copies, so the state it describes is frozen however
        late the writer runs it). The training thread pays one enqueue —
        capture, CRC, serialize, fsync, and any brownout's retry backoff
        all happen behind it. Delta planning moves to the writer too
        (the single serial consumer: queue order = save order = chain
        order). Requires fully-addressable state — the multi-controller
        dump's ``replicate_to_mesh`` is a collective and must stay on
        the training thread (the caller gates on this).

        Returns the nominal full-snapshot path; the writer may publish
        a delta instead (the chain plan runs after capture)."""
        t0 = time.perf_counter()
        path = self._path(int(step))
        self._enqueue(("deferred", int(step), collect, touched_rows),
                      int(step), path, when_full)
        _obs_metric("observe", "checkpoint.dump_seconds",
                    time.perf_counter() - t0)
        return path

    def _enqueue(self, item, step: int, path: str,
                 when_full: str | None) -> bool:
        """Place one save in the queue slot. Returns True when enqueued;
        False when the slot stayed full and ``when_full='degrade'``
        turned the save into a SKIP (degraded-publish accounting — the
        training thread never waits on a wedged writer)."""
        mode = self.when_full if when_full is None else when_full
        deferred = item[0] == "deferred"
        with self._cv:
            self._raise_pending_error()
            if (mode == "degrade" and self._queued is not None
                    and not self._closed):
                self.degraded_publishes += 1
                self._publish_backlog += 1
                self._degraded_chain = True
                backlog = self._publish_backlog
            else:
                backlog = None
                while self._queued is not None and not self._closed:
                    self._cv.wait()
                    self._raise_pending_error()
                if self._closed:
                    raise RuntimeError(
                        f"AsyncCheckpointer for {self.dir} is closed")
                self._queued = item
                if deferred:
                    self._unplanned += 1
                # Emitted while still HOLDING the cv (the writer can't
                # pop the slot until we release), so the journal's
                # enqueued → saved ordering holds even for an
                # instantaneous write. No lock cycle: the writer takes
                # the recorder lock only from _write, never while
                # waiting on this cv.
                _obs_event("checkpoint_enqueued", step=step, path=path,
                           **({"capture": "writer"} if deferred else {}))
                _obs_metric("inc", "checkpoint.enqueues", 1)
                self._cv.notify_all()
        if backlog is not None:
            _log.warning(
                "checkpoint publish step %d DEGRADED (writer busy; "
                "backlog %d)", step, backlog)
            _obs_event("checkpoint_degraded", step=step, backlog=backlog,
                       error="writer busy (queue slot full)")
            _obs_metric("inc", "storage.degraded_publishes", 1)
            _obs_metric("set", "checkpoint.publish_backlog", backlog)
            return False
        return True

    def flush(self) -> None:
        with self._cv:
            while self._queued is not None or self._writing:
                self._cv.wait()
            self._raise_pending_error()

    def close(self) -> None:
        try:
            self.flush()
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._writer.join(timeout=60.0)

    def _raise_pending_error(self) -> None:
        # Called under self._cv.
        if self._error is not None:
            err, self._error = self._error, None
            # The failed write may have been a planned chain link: later
            # deltas must not chain onto a publication that never landed.
            self._chain_reset()
            raise RuntimeError(
                f"background checkpoint write failed under {self.dir}"
            ) from err

    # -- read side (must observe accepted saves) --------------------------

    def read_snapshot(self, step: int | None = None, *, verify: bool = True):
        self.flush()
        return super().read_snapshot(step, verify=verify)

    def verify_snapshot(self, step: int | None = None) -> bool:
        self.flush()
        return super().verify_snapshot(step)

    def latest_valid_step(self) -> int | None:
        self.flush()
        return super().latest_valid_step()

    # -- writer thread ----------------------------------------------------

    def _degradable(self, e: BaseException) -> bool:
        """True when a failed publish may be SKIPPED (degraded) rather
        than surfaced as a caller error: transient storage errors after
        the retry budget, and the orphan-delta refusal that follows a
        degraded base. A fence refusal anywhere in the cause chain is
        never degradable — a zombie of an aborted pod attempt must die
        loudly, not quietly skip publishes forever."""
        from fps_tpu.supervise.child import StaleEpochError

        cause = e
        while cause is not None:
            if isinstance(cause, StaleEpochError):
                return False
            cause = cause.__cause__
        if isinstance(e, OrphanDeltaError):
            return True
        if isinstance(e, OSError) and e.errno == _errno_mod.ENOENT:
            # ENOENT is retry-worthy (a just-renamed file can be
            # transiently invisible on a caching mount) but NOT
            # degrade-worthy: persisting past the whole retry budget
            # means the checkpoint DIRECTORY is gone — silently
            # skipping every publish would end the run "successfully"
            # with zero durable state. Fail loudly instead.
            return False
        return _retry.classify_error(e) == "retryable"

    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while self._queued is None and not self._closed:
                    self._cv.wait()
                if self._queued is None:  # closed and drained
                    return
                item = self._queued
                self._queued = None
                self._writing = True
                self._cv.notify_all()  # free the queue slot for save()
            arrays = None
            try:
                if item[0] == "deferred":
                    _, step, collect, touched_rows = item
                    try:
                        t0 = time.perf_counter()
                        arrays = _run_capture(collect)
                        _obs_metric("observe", "checkpoint.capture_seconds",
                                    time.perf_counter() - t0)
                        with self._cv:
                            if self._degraded_chain:
                                self._chain_reset()
                                self._degraded_chain = False
                        step, base, arrays = self._plan_publication(
                            step, arrays, touched_rows)
                    finally:
                        # Planned (or failed trying): an inline save()
                        # waiting to plan may proceed. On failure the
                        # chain resets below/with the surfaced error.
                        with self._cv:
                            self._unplanned -= 1
                            self._cv.notify_all()
                else:
                    _, step, base, arrays = item
                self._write(step, arrays, base=base)
                if self._publish_backlog:
                    # Recovery: a landed publish is a FULL description
                    # of its step (or a delta whose chain landed), so
                    # the whole backlog of skipped recency drains here.
                    with self._cv:
                        self._publish_backlog = 0
                    _obs_metric("set", "checkpoint.publish_backlog", 0)
                    _obs_event("checkpoint_backlog_drained",
                               step=int(step))
            except BaseException as e:  # noqa: BLE001 - re-raised on caller
                if self.degrade and self._degradable(e):
                    # Degraded-mode storage: SKIP the publish instead of
                    # poisoning the caller — training keeps running on
                    # last-good durable state, the backlog gauge and the
                    # storage-staleness SLO carry the cost (lost
                    # recency, never corruption or a crash).
                    with self._cv:
                        self.degraded_publishes += 1
                        self._publish_backlog += 1
                        self._degraded_chain = True
                        backlog = self._publish_backlog
                    _log.warning(
                        "checkpoint publish step %d DEGRADED (skipped "
                        "after retries; backlog %d): %r", step, backlog,
                        e)
                    _obs_event("checkpoint_degraded", step=int(step),
                               backlog=backlog, error=repr(e))
                    _obs_metric("inc", "storage.degraded_publishes", 1)
                    _obs_metric("set", "checkpoint.publish_backlog",
                                backlog)
                else:
                    with self._cv:
                        if self._error is None:
                            self._error = e
                        else:
                            # Keep the FIRST failure (the root cause): a
                            # derived refusal — e.g. the orphan-delta
                            # guard firing because the base's write just
                            # failed — must not mask the original error.
                            _log.warning(
                                "suppressing follow-on checkpoint write "
                                "error (first failure pending): %r", e)
            finally:
                # Drop the buffers (and a deferred item's on-device
                # boundary copies) before blocking on the cv.
                del arrays, item
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()


def fence_epoch_from_env() -> int | None:
    """The pod fencing epoch of this process (``FPS_TPU_POD_EPOCH``), or
    None when not running under a pod — pass as ``Checkpointer(...,
    fence_epoch=...)`` so a pod child's publishes honor the fence."""
    from fps_tpu.supervise import child as _pod

    return _pod.pod_env()["epoch"]


def _run_capture(collect):
    """Writer-thread capture seam: runs a deferred save's ``collect()``
    (the device→host dump over on-device boundary copies). Module-level
    — like ``_atomic_savez`` — so the chaos harness can monkeypatch a
    SIGKILL into the middle of a background capture and prove the
    resume contract holds for the deferred delta chain too."""
    return collect()


# ---------------------------------------------------------------------------
# Atomic file helpers (a torn write must not corrupt the latest snapshot).
# ---------------------------------------------------------------------------

def _atomic_savez(path: str, arrays: Mapping[str, np.ndarray],
                  precommit=None) -> None:
    """Serialize + fsync + atomic rename: after this returns, ``path``
    either holds the complete snapshot or (on a crash anywhere inside)
    its previous content — never a torn file. The fsync BEFORE the rename
    is what makes the rename a real durability point (a power loss after
    an unfsync'd rename can publish an empty file); the directory fsync
    after makes the rename itself survive. ``precommit`` (optional) runs
    after the fsync and immediately before the publishing rename; if it
    raises, nothing is published (the pod fence hook).

    Fault seams (``fps_tpu.core.retry.fault_check``): the deterministic
    injector may fail/slow the serialize, the fsync, or the rename —
    and a ``"torn"`` rename directive publishes a truncated prefix at
    the destination before failing, the hostile-rename case the CRC
    gates downstream must catch. A failed attempt always removes its
    tmp file, so retries start clean."""
    _retry.fault_check("write", path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            _retry.fault_check("fsync", path)
            os.fsync(f.fileno())
        if precommit is not None:
            precommit()
        if _retry.fault_check("replace", path) == "torn":
            with open(tmp, "rb") as src, open(path, "wb") as dst:
                dst.write(src.read(max(1, os.path.getsize(tmp) // 3)))
            raise OSError(_errno_mod.EIO,
                          "injected torn rename (truncated publish)",
                          path)
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # best-effort: not every filesystem supports dir fsync
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


