"""Overlapped host pipeline: background chunk prefetch + placement.

The compiled pull→compute→push loop is one fused dispatch, but the host
driver around it was fully serial: assemble the next chunk (numpy fancy
indexing in :mod:`fps_tpu.core.ingest`), place it onto the batch sharding
(``host_to_sharded``), dispatch, block for whatever consumer needs host
metrics, repeat. Every one of those host segments is time the device
spends idle — BENCH round 5 measured ~28% of the MF epoch as exactly this
gap (0.63 s/epoch against a 0.49 s fused-loop floor).

:class:`ChunkPrefetcher` closes the ingest+place part of the gap: a
single worker thread pulls from any chunk iterator, runs host assembly
AND host→device placement up to ``depth`` chunks ahead, and hands the
driver already-device-resident chunks (wrapped in :class:`PlacedChunk`
so ``Trainer.run_chunk`` skips its place phase) in the exact order the
source yielded them. The training numerics cannot change: placement
produces the same sharded arrays the synchronous path would, the
compiled program is looked up from the same cache, and chunk order is
preserved — prefetch on/off is bit-identical (tested, including the
lowered HLO).

Contracts:

* **deterministic order** — one worker thread, FIFO buffer: chunks come
  out in source order, always.
* **bounded depth** — at most ``depth`` placed chunks are buffered (plus
  the one being assembled); the worker blocks when the buffer is full,
  so host and device memory stay bounded on an unbounded stream.
* **errors re-raise on the caller** — an exception inside the source
  iterator (or placement) is delivered at the position it occurred:
  every chunk assembled before it is yielded first, then the original
  exception object is raised from ``__next__`` on the consuming thread.
* **no thread leaks** — :meth:`close` wakes a blocked worker and joins
  it; every exit path of ``Trainer.fit_stream`` (normal end, a raising
  ``on_chunk``, health abort, quarantine-budget abort) closes the
  pipeline in a ``finally``. The thread is a daemon as a last resort, so
  even an unjoinable worker (source wedged in a blocking read) cannot
  hang interpreter exit.

Telemetry (all optional): a :class:`~fps_tpu.obs.timing.PhaseTimer` gets
the worker's assemble+place seconds folded in as the ``prefetch`` phase,
and a :class:`~fps_tpu.obs.registry.Recorder` gets a
``prefetch.queue_depth`` gauge plus a ``prefetch.chunks`` counter — the
evidence ``tools/obs_report.py`` and ``bench.py`` render as the overlap
breakdown.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Callable, Iterable

_log = logging.getLogger("fps_tpu.prefetch")

# Worker→consumer end-of-stream marker (never buffered, never yielded).
_END = object()

#: Adaptive depth: consumed chunks per adaptation window, and the
#: queue-empty stall count within one window that triggers a raise.
ADAPT_WINDOW = 8
ADAPT_STALLS = 2

#: A depth raise must keep the whole buffer under this share of the
#: currently-available host memory.
ADAPT_MEM_SHARE = 0.25


def _available_host_bytes() -> int | None:
    """Available (not merely free) host memory, or ``None`` when the
    platform can't say — ``None`` means the memory veto abstains."""
    try:
        return os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return None


def _chunk_nbytes(item) -> int:
    """Dependency-free byte estimate of one buffered chunk (device
    arrays count too — a placed chunk's device footprint tracks its
    host footprint, and overestimating only makes the veto stricter)."""
    if isinstance(item, PlacedChunk):
        return _chunk_nbytes(item.batches) + _chunk_nbytes(item.host_ids)
    if isinstance(item, dict):
        return sum(_chunk_nbytes(v) for v in item.values())
    if isinstance(item, (list, tuple)):
        return sum(_chunk_nbytes(v) for v in item)
    try:
        return int(getattr(item, "nbytes", 0) or 0)
    except TypeError:
        return 0


class PlacedChunk:
    """A chunk already placed on the batch sharding by the pipeline.

    ``Trainer.run_chunk`` unwraps it and skips the place phase — the
    wrapper exists so an already-uploaded chunk can never be mistaken
    for a host chunk and re-placed (or worse, a host chunk silently
    skip placement).

    ``host_ids`` optionally carries the raw host id columns the
    compacted-cold-route certifier needs
    (``WorkerLogic.pulled_ids_host``): placement happens on the prefetch
    worker thread, but hot-set membership can change between placement
    and dispatch (re-ranks), so certification itself runs at dispatch
    time against these retained host arrays — references to the source
    chunk's columns, not copies.
    """

    __slots__ = ("batches", "host_ids")

    def __init__(self, batches, host_ids=None):
        self.batches = batches
        self.host_ids = host_ids


class ChunkPrefetcher:
    """Bounded-depth background prefetch+place over a chunk iterator.

    Args:
      chunks: any iterator/iterable of chunks (host pytrees or
        device-resident chunks — both flow through with unchanged
        semantics).
      place_fn: optional host→device placement (e.g. the driver's batch
        upload); when given, yielded items are :class:`PlacedChunk`
        wrappers around its result. ``None`` overlaps assembly only.
      depth: max chunks buffered ahead (>= 1; default 2 — one in flight
        on the device, one ready, one being assembled). With
        ``max_depth`` set this is the STARTING depth.
      max_depth: enable adaptive depth — when the consumer keeps
        draining the buffer empty (>= ``ADAPT_STALLS`` queue-empty
        stalls inside a window of ``ADAPT_WINDOW`` consumed chunks) the
        depth is raised one chunk at a time up to this bound, provided
        the grown buffer stays under ``ADAPT_MEM_SHARE`` of available
        host memory. Each raise increments the
        ``prefetch.depth_adjustments`` counter. ``None`` (default)
        keeps the fixed-depth behavior. Depth never adapts downward:
        the buffer bound is what certifies memory, and a transiently
        fast consumer should keep the headroom it earned.
      mem_probe: available-host-bytes callable for the memory veto
        (test seam; default reads ``SC_AVPHYS_PAGES``; returning
        ``None`` abstains).
      recorder: optional :class:`fps_tpu.obs.Recorder` for the
        ``prefetch.queue_depth`` gauge and ``prefetch.chunks`` counter.
      timer: optional :class:`fps_tpu.obs.PhaseTimer`; worker seconds are
        folded in under the ``prefetch`` phase (thread-safe).
      start_index: stream index of the first chunk (``fit_stream``'s
        ``start_step`` on a resume) — only used to key ``skip_place``.
      skip_place: stream indices whose chunks are yielded UNPLACED (raw)
        — the driver's preset-quarantine set: those chunks are consumed
        but never dispatched, so paying their host→device upload would
        be pure waste.

    Iterate it like the source iterator; call :meth:`close` (or use it
    as a context manager) on every exit path.
    """

    def __init__(self, chunks: Iterable, place_fn: Callable | None = None, *,
                 depth: int = 2, max_depth: int | None = None,
                 mem_probe: Callable | None = None, recorder=None,
                 timer=None, start_index: int = 0,
                 skip_place=frozenset(), name: str = "fps-prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if max_depth is not None and max_depth < depth:
            raise ValueError(
                f"prefetch max_depth={max_depth} must be >= depth={depth}")
        self.depth = depth
        self.max_depth = max_depth
        self._mem_probe = (mem_probe if mem_probe is not None
                           else _available_host_bytes)
        self._stalls = 0
        self._consumed = 0
        self._it = iter(chunks)
        self._place = place_fn
        self._index = start_index
        self._skip_place = frozenset(skip_place)
        self._rec = recorder
        self._timer = timer
        self._cv = threading.Condition()
        self._buf: collections.deque = collections.deque()
        self._done = False
        self._stop = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True
        )
        self._thread.start()

    # -- worker side ------------------------------------------------------

    def _gauge(self, depth: int) -> None:
        # Called OUTSIDE self._cv: recorder sinks may do file I/O, which
        # must not serialize the producer/consumer handoff.
        if self._rec is not None:
            self._rec.set("prefetch.queue_depth", float(depth))

    def _worker(self) -> None:
        try:
            while True:
                with self._cv:
                    while len(self._buf) >= self.depth and not self._stop:
                        self._cv.wait()
                    if self._stop:
                        return
                t0 = time.perf_counter()
                item = next(self._it, _END)
                if (item is not _END and self._place is not None
                        and self._index not in self._skip_place):
                    placed = self._place(item)
                    # A place_fn may return a ready PlacedChunk itself
                    # (the driver's certifying wrapper does, to attach
                    # host_ids); only wrap bare batch pytrees.
                    item = (placed if isinstance(placed, PlacedChunk)
                            else PlacedChunk(placed))
                self._index += 1
                dt = time.perf_counter() - t0
                if item is not _END:
                    if self._timer is not None:
                        self._timer.add("prefetch", dt)
                    if self._rec is not None:
                        self._rec.inc("prefetch.chunks")
                with self._cv:
                    if self._stop:
                        return
                    if item is _END:
                        self._done = True
                    else:
                        self._buf.append(item)
                        depth = len(self._buf)
                    self._cv.notify_all()
                if item is _END:
                    return
                self._gauge(depth)
        except BaseException as e:  # noqa: BLE001 - re-raised on consumer
            with self._cv:
                self._error = e
                self._done = True
                self._cv.notify_all()

    # -- consumer side ----------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        raised = False
        with self._cv:
            if not self._buf and not self._done:
                # The device is about to idle waiting on the host
                # pipeline — the signal adaptive depth sizes from.
                self._stalls += 1
            while not self._buf and not self._done:
                self._cv.wait()
            if self._buf:
                item = self._buf.popleft()
                depth = len(self._buf)
                self._cv.notify_all()  # free a slot for the worker
            elif self._error is not None:
                err, self._error = self._error, None
                # The original exception OBJECT (traceback included)
                # crosses threads; the stream is dead past this point.
                raise err
            else:
                raise StopIteration
            self._consumed += 1
            if self._consumed >= ADAPT_WINDOW:
                raised = self._maybe_raise_depth_locked(item)
                self._stalls = 0
                self._consumed = 0
        self._gauge(depth)
        if raised and self._rec is not None:
            # Outside the cv, like _gauge: sinks may do file I/O.
            self._rec.inc("prefetch.depth_adjustments")
        return item

    def _maybe_raise_depth_locked(self, item) -> bool:
        """One-chunk depth raise at a window boundary (cv held):
        stall-justified and memory-vetoed."""
        if self.max_depth is None or self.depth >= self.max_depth:
            return False
        if self._stalls < ADAPT_STALLS:
            return False
        nbytes = _chunk_nbytes(item)
        avail = self._mem_probe()
        if (avail is not None and nbytes > 0
                and (self.depth + 1) * nbytes > ADAPT_MEM_SHARE * avail):
            return False
        self.depth += 1
        self._cv.notify_all()  # the worker may now run further ahead
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Stop the worker and join it (idempotent).

        Buffered chunks are dropped. A worker blocked on the full buffer
        is woken; one blocked inside the SOURCE (a wedged ``next``)
        cannot be preempted from Python — after ``timeout`` seconds it
        is left as a daemon to die with the process (logged)."""
        with self._cv:
            self._stop = True
            self._buf.clear()
            self._cv.notify_all()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            _log.warning(
                "prefetch worker did not exit within %.1fs (source blocked "
                "in next()?); leaving the daemon thread behind", timeout,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
