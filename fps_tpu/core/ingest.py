"""Host-side streaming ingest — the replacement for the Flink DataStream source.

The reference consumes an unbounded ``DataStream[T]``; parallelism comes from
Flink splitting the source across worker subtasks, and data locality (e.g.
matrix factorization keeping user vectors in worker state) comes from how the
stream is partitioned before ``FlinkParameterServer.transform``.

Here ingest is a plain Python iterator producing fixed-shape *chunks* (a
``scan``-able stack of microbatches) that the compiled driver consumes. Key
responsibilities:

* **routing**: optionally place each example on the worker that owns its
  route key (``route_key % num_workers == worker_index``), preserving the
  reference's worker-local-state locality trick;
* **static shapes**: every chunk has identical shape; short queues are
  padded with zero-weight examples (the ``weight`` field), so XLA compiles
  the step exactly once;
* **epochs vs one-pass**: the reference is one-pass streaming; wrapping the
  iterator for multiple epochs gives the multi-epoch mode the benchmarks
  need.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np


def epoch_chunks(
    data: Mapping[str, np.ndarray],
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    route_key: str | None = None,
    sync_every: int | None = None,
    seed: int | None = 0,
    drop_remainder: bool = False,
) -> Iterator[dict]:
    """Yield fixed-shape chunks covering one (shuffled) pass over ``data``.

    Args:
      data: columnar examples — dict of equal-length 1-D/2-D arrays.
      num_workers: total worker devices (mesh data*shard).
      local_batch: examples per worker per step.
      steps_per_chunk: microbatch steps stacked per compiled call. For SSP
        mode this must be a multiple of ``sync_every``.
      route_key: name of an integer column; examples are routed to worker
        ``value % num_workers``. ``None`` routes round-robin.
      sync_every: if set, chunks are shaped ``(R, sync_every, B, ...)`` for
        the SSP driver instead of ``(T, B, ...)``.
      seed: shuffle seed (None = no shuffle, stream order preserved, which
        matches the reference's online one-pass semantics).
      drop_remainder: drop the final partial chunk instead of padding it.

    Yields:
      dict with the columns of ``data`` plus ``weight`` (1.0 real, 0.0 pad),
      each shaped ``(T, B, ...)`` or ``(R, s, B, ...)``; the batch dim ``B``
      is ordered worker-major (worker 0's rows first), matching the
      ``P(None, ('data','shard'))`` batch sharding.
    """
    n = len(next(iter(data.values())))
    for k, v in data.items():
        if len(v) != n:
            raise ValueError(f"column {k!r} length {len(v)} != {n}")

    order = np.arange(n)
    if seed is not None:
        np.random.default_rng(seed).shuffle(order)

    if route_key is not None:
        keys = np.asarray(data[route_key])[order]
        queues = [order[keys % num_workers == w] for w in range(num_workers)]
    else:
        queues = [order[w::num_workers] for w in range(num_workers)]

    steps_total = max(-(-len(q) // local_batch) for q in queues)
    if sync_every is not None:
        if steps_per_chunk % sync_every:
            raise ValueError("steps_per_chunk must be a multiple of sync_every")
        steps_total = -(-steps_total // sync_every) * sync_every
    if drop_remainder:
        steps_total = (steps_total // steps_per_chunk) * steps_per_chunk
    else:
        steps_total = -(-steps_total // steps_per_chunk) * steps_per_chunk
    if steps_total == 0:
        return

    # Pad every queue to steps_total*local_batch with sentinel -1.
    full = steps_total * local_batch
    idx = np.full((num_workers, full), -1, dtype=np.int64)
    for w, q in enumerate(queues):
        idx[w, : min(len(q), full)] = q[:full]
    # (steps_total, num_workers, local_batch) -> (steps_total, B)
    idx = idx.reshape(num_workers, steps_total, local_batch).transpose(1, 0, 2)
    idx = idx.reshape(steps_total, num_workers * local_batch)

    weight = (idx >= 0).astype(np.float32)
    safe = np.maximum(idx, 0)

    for start in range(0, steps_total, steps_per_chunk):
        sl = slice(start, start + steps_per_chunk)
        chunk = {k: np.asarray(v)[safe[sl]] for k, v in data.items()}
        chunk["weight"] = weight[sl]
        if sync_every is not None:
            chunk = {
                k: v.reshape((-1, sync_every) + v.shape[1:]) for k, v in chunk.items()
            }
        yield chunk


def multi_epoch_chunks(data, epochs: int, *, seed: int | None = 0, **kw):
    """Repeat :func:`epoch_chunks` for several epochs with distinct shuffles."""
    for e in range(epochs):
        eseed = None if seed is None else seed + e
        yield from epoch_chunks(data, seed=eseed, **kw)
