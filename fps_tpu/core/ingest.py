"""Host-side streaming ingest — the replacement for the Flink DataStream source.

The reference consumes an unbounded ``DataStream[T]``; parallelism comes from
Flink splitting the source across worker subtasks, and data locality (e.g.
matrix factorization keeping user vectors in worker state) comes from how the
stream is partitioned before ``FlinkParameterServer.transform``.

Here ingest is a plain Python iterator producing fixed-shape *chunks* (a
``scan``-able stack of microbatches) that the compiled driver consumes. Key
responsibilities:

* **routing**: optionally place each example on the worker that owns its
  route key (``route_key % num_workers == worker_index``), preserving the
  reference's worker-local-state locality trick;
* **static shapes**: every chunk has identical shape; short queues are
  padded with zero-weight examples (the ``weight`` field), so XLA compiles
  the step exactly once;
* **epochs vs one-pass**: the reference is one-pass streaming; wrapping the
  iterator for multiple epochs gives the multi-epoch mode the benchmarks
  need.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np


def per_worker_cold_counts(
    ids: np.ndarray,
    num_workers: int,
    *,
    hot_head: int = 0,
    hot_member: np.ndarray | None = None,
) -> np.ndarray:
    """Per-(step, worker) cold-id counts of one chunk id column — the
    host half of the compacted cold route's certification
    (``TableSpec.cold_budget``; the ``head_prefix`` pattern applied to
    payload-proportional routing).

    ``ids`` is any array whose LAST axis is the global batch dim
    (worker-major, ``W * local_batch`` — the chunk column layout);
    leading axes are step dims. Hot membership is either the static
    frequency-ranked head (``id < hot_head``) or an explicit boolean
    ``hot_member`` array of length ``num_ids + 1`` (the adaptive tier's
    current hot set; out-of-range ids clamp onto the trailing False
    sentinel). Negative ids never count (the -1 padding contract);
    everything else is counted conservatively, exactly as the device
    compaction sees it.

    Returns an ``(steps, num_workers)`` int array of cold counts — the
    certifier compares its max against the lane budget.
    """
    a = np.asarray(ids)
    B = a.shape[-1]
    if B % num_workers:
        raise ValueError(
            f"batch dim {B} not divisible by num_workers={num_workers}")
    per_worker = a.reshape(-1, num_workers, B // num_workers)
    if hot_member is not None:
        member = np.asarray(hot_member, bool)
        cold = (per_worker >= 0) & ~member[
            np.clip(per_worker, 0, len(member) - 1)]
    else:
        cold = per_worker >= hot_head
    return cold.sum(axis=-1)


def _to_ssp_shape(chunk: dict, sync_every: int) -> dict:
    """Reshape (T, B, ...) chunk leaves to (T//s, s, B, ...) for the SSP driver."""
    return {
        k: v.reshape((-1, sync_every) + v.shape[1:]) for k, v in chunk.items()
    }


def epoch_chunks(
    data: Mapping[str, np.ndarray],
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    route_key: str | None = None,
    sync_every: int | None = None,
    seed: int | None = 0,
    drop_remainder: bool = False,
) -> Iterator[dict]:
    """Yield fixed-shape chunks covering one (shuffled) pass over ``data``.

    Args:
      data: columnar examples — dict of equal-length 1-D/2-D arrays.
      num_workers: total worker devices (mesh data*shard).
      local_batch: examples per worker per step.
      steps_per_chunk: microbatch steps stacked per compiled call. For SSP
        mode this must be a multiple of ``sync_every``.
      route_key: name of an integer column; examples are routed to worker
        ``value % num_workers``. ``None`` routes round-robin.
      sync_every: if set, chunks are shaped ``(R, sync_every, B, ...)`` for
        the SSP driver instead of ``(T, B, ...)``.
      seed: shuffle seed (None = no shuffle, stream order preserved, which
        matches the reference's online one-pass semantics).
      drop_remainder: drop the final partial chunk instead of padding it.

    Yields:
      dict with the columns of ``data`` plus ``weight`` (1.0 real, 0.0 pad),
      each shaped ``(T, B, ...)`` or ``(R, s, B, ...)``; the batch dim ``B``
      is ordered worker-major (worker 0's rows first), matching the
      ``P(None, ('data','shard'))`` batch sharding.
    """
    n = len(next(iter(data.values())))
    for k, v in data.items():
        if len(v) != n:
            raise ValueError(f"column {k!r} length {len(v)} != {n}")
    # Materialize every column ONCE — the per-chunk loop below used to
    # re-run np.asarray on each column for every chunk, a full-array copy
    # per chunk whenever the caller passed lists/memmaps.
    arrays = {k: np.asarray(v) for k, v in data.items()}

    order = np.arange(n)
    if seed is not None:
        np.random.default_rng(seed).shuffle(order)

    if route_key is not None:
        keys = arrays[route_key][order]
        queues = [order[keys % num_workers == w] for w in range(num_workers)]
    else:
        queues = [order[w::num_workers] for w in range(num_workers)]

    steps_total = max(-(-len(q) // local_batch) for q in queues)
    if sync_every is not None:
        if steps_per_chunk % sync_every:
            raise ValueError("steps_per_chunk must be a multiple of sync_every")
        steps_total = -(-steps_total // sync_every) * sync_every
    if drop_remainder:
        steps_total = (steps_total // steps_per_chunk) * steps_per_chunk
    else:
        steps_total = -(-steps_total // steps_per_chunk) * steps_per_chunk
    if steps_total == 0:
        return

    # Pad every queue to steps_total*local_batch with sentinel -1.
    full = steps_total * local_batch
    idx = np.full((num_workers, full), -1, dtype=np.int64)
    for w, q in enumerate(queues):
        idx[w, : min(len(q), full)] = q[:full]
    # (steps_total, num_workers, local_batch) -> (steps_total, B)
    idx = idx.reshape(num_workers, steps_total, local_batch).transpose(1, 0, 2)
    idx = idx.reshape(steps_total, num_workers * local_batch)

    weight = (idx >= 0).astype(np.float32)
    safe = np.maximum(idx, 0)

    for start in range(0, steps_total, steps_per_chunk):
        sl = slice(start, start + steps_per_chunk)
        chunk = {k: a[safe[sl]] for k, a in arrays.items()}
        chunk["weight"] = weight[sl]
        if sync_every is not None:
            chunk = _to_ssp_shape(chunk, sync_every)
        yield chunk


def multi_epoch_chunks(data, epochs: int, *, seed: int | None = 0, **kw):
    """Repeat :func:`epoch_chunks` for several epochs with distinct shuffles."""
    for e in range(epochs):
        eseed = None if seed is None else seed + e
        yield from epoch_chunks(data, seed=eseed, **kw)


def stream_chunks(
    source,
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    route_key: str | None = None,
    sync_every: int | None = None,
) -> Iterator[dict]:
    """Fixed-shape chunks from an **unbounded** stream of example batches.

    The reference consumes an unbounded ``DataStream[T]`` — training runs as
    long as the source produces records, and terminates via the
    ``iterationWaitTime`` timeout when the stream dries up. This is the
    analog for a compiled loop: ``source`` is any iterator yielding columnar
    dicts (arbitrary, varying lengths — e.g. a socket reader, file tailer,
    or Kafka-style consumer poll loop), and chunks are emitted as soon as
    enough examples have buffered; when the source is exhausted, the
    remainder is flushed with zero-weight padding.

    Routing matches :func:`epoch_chunks`: ``route_key`` pins each example to
    worker ``value % num_workers`` (worker-local-state locality); ``None``
    spreads round-robin. Under keyed routing a skewed stream makes some
    workers run ahead — short queues are padded per chunk (weight 0), which
    is exactly the reference's behavior of workers idling while others have
    records in flight.
    """
    if sync_every is not None and steps_per_chunk % sync_every:
        raise ValueError("steps_per_chunk must be a multiple of sync_every")
    capacity = steps_per_chunk * local_batch  # per worker
    queues: list[dict[str, list]] | None = None
    counts = [0] * num_workers
    columns: dict[str, tuple] = {}  # name -> (trailing shape, dtype)
    rr = 0  # round-robin cursor

    def emit():
        out = {}
        for k, (trail, dtype) in columns.items():
            per_worker = []
            for w in range(num_workers):
                col = (
                    np.concatenate(queues[w][k])
                    if queues[w][k]
                    else np.zeros((0,) + trail, dtype)
                )
                take, rest = col[:capacity], col[capacity:]
                queues[w][k] = [rest] if len(rest) else []
                pad = capacity - len(take)
                if pad:
                    take = np.concatenate(
                        [take, np.zeros((pad,) + trail, dtype)]
                    )
                per_worker.append(
                    take.reshape((steps_per_chunk, local_batch) + trail)
                )
            # (steps, num_workers*local_batch, ...), worker-major per step.
            out[k] = np.stack(per_worker, axis=1).reshape(
                (steps_per_chunk, num_workers * local_batch) + trail
            )
        weights = []
        for w in range(num_workers):
            n = min(counts[w], capacity)
            wcol = np.zeros(capacity, np.float32)
            wcol[:n] = 1.0
            counts[w] -= n
            weights.append(wcol.reshape(steps_per_chunk, local_batch))
        out["weight"] = np.stack(weights, axis=1).reshape(steps_per_chunk, -1)
        if sync_every is not None:
            out = _to_ssp_shape(out, sync_every)
        return out

    for batch in source:
        if "weight" in batch:
            raise ValueError(
                "'weight' is reserved: stream_chunks emits it as the "
                "real-vs-padding mask; carry importance weights in a "
                "differently-named column"
            )
        if queues is None:
            columns = {
                k: (np.asarray(v).shape[1:], np.asarray(v).dtype)
                for k, v in batch.items()
            }
            queues = [{k: [] for k in columns} for _ in range(num_workers)]
        if set(batch.keys()) != set(columns.keys()):
            raise ValueError(
                f"batch columns {sorted(batch)} != first batch's schema "
                f"{sorted(columns)} — the schema is pinned by the first batch"
            )
        n = len(next(iter(batch.values())))
        arrs = {}
        for k, (trail, dtype) in columns.items():
            # Pin every batch to the first batch's dtype/shape so each chunk
            # compiles to the same program (the static-shape contract).
            a = np.asarray(batch[k]).astype(dtype, copy=False)
            if len(a) != n or a.shape[1:] != trail:
                raise ValueError(
                    f"column {k!r} shape {a.shape} inconsistent with "
                    f"batch length {n} / trailing shape {trail}"
                )
            arrs[k] = a
        if route_key is not None:
            dest = arrs[route_key] % num_workers
        else:
            dest = (np.arange(n) + rr) % num_workers
            rr = (rr + n) % num_workers
        for w in range(num_workers):
            sel = dest == w
            m = int(sel.sum())
            if not m:
                continue
            for k in columns:
                queues[w][k].append(arrs[k][sel])
            counts[w] += m
        while max(counts) >= capacity:
            yield emit()
    if queues is not None and any(counts):
        yield emit()
