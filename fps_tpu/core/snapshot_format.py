"""The on-disk snapshot contract — naming, integrity, zero-copy reads.

One jax-FREE module (stdlib + numpy) owning everything three consumers
must agree on about a published ``ckpt_*.npz`` snapshot:

* the **training plane** (:mod:`fps_tpu.core.checkpoint`) writes
  snapshots and restores them (it re-exports the names below, so nothing
  upstream changed);
* the **chaos injectors** (:mod:`fps_tpu.testing.chaos`) corrupt them by
  the same filename contract;
* the **serving plane** (:mod:`fps_tpu.serve`) — a jax-optional process
  that must discover, CRC-verify, and map snapshots on a machine that
  may not even have an accelerator runtime installed. Putting the
  contract here (instead of importing the jax-laden checkpoint module)
  is what makes that possible.

Integrity is the checkpoint layer's scheme verbatim: every array entry
``k`` carries a ``meta::crc::k`` CRC-32 tag written at save time;
:func:`verify_snapshot_file` checks every entry the way
``Checkpointer._read_verified`` does (structural read errors and
checksum mismatches both fail), but reports ``(ok, reason)`` instead of
raising the jax-layer's ``SnapshotCorruptionError``.

Zero-copy reads: ``np.savez`` writes an UNCOMPRESSED zip of ``.npy``
members, so each array's bytes sit contiguously at a knowable file
offset. :func:`map_snapshot_arrays` parses the zip's local headers plus
each member's npy header and returns read-only ``np.memmap`` views — a
multi-GB table "loads" in microseconds and costs no resident memory
until rows are touched. This is what makes a serving hot-swap a pointer
flip whose latency is independent of table size.
"""

from __future__ import annotations

import os
import re
import struct
import zipfile
import zlib

import numpy as np

from fps_tpu.core import retry as _retry

__all__ = [
    "SNAPSHOT_RE", "SNAPSHOT_FMT", "SEP", "TABLE_PREFIX", "LS_PREFIX",
    "FOLD_PREFIX", "MESH_SHAPE_KEY", "POD_EPOCH_KEY",
    "CRC_PREFIX", "IO_ERRORS", "array_crc32", "snapshot_path",
    "snapshot_steps", "verify_snapshot_file", "latest_valid_snapshot",
    "map_snapshot_arrays",
    # Delta-snapshot chains (ISSUE 14): jax-free chain discovery,
    # verification, and resolution shared by the checkpoint layer, the
    # serving plane, and the chaos harness.
    "DELTA_RE", "DELTA_FMT", "BASE_STEP_KEY", "DELTA_IDS_PREFIX",
    "DELTA_ROWS_PREFIX", "NO_SUCH_FILE", "ChainError", "Publication",
    "delta_path", "publications", "chain_members", "read_pub_meta",
    "verify_chain", "latest_valid_chain", "read_delta_arrays",
    "apply_delta_entries", "resolve_chain_entries",
]

# Snapshot filename contract — the single source of truth (the
# checkpoint layer and the chaos injectors import these from here or via
# fps_tpu.core.checkpoint's re-export).
SNAPSHOT_RE = re.compile(r"ckpt_(\d{12})\.npz")
SNAPSHOT_FMT = "ckpt_{step:012d}.npz"
# Delta publication filename contract: ``delta_{step}_{base}.npz`` — the
# base step rides the NAME so chain walking is a pure directory listing
# (no file opens); the authoritative link is the CRC-tagged
# ``meta::base_step`` entry inside, cross-checked by every reader.
DELTA_RE = re.compile(r"delta_(\d{12})_(\d{12})\.npz")
DELTA_FMT = "delta_{step:012d}_{base:012d}.npz"

# npz key layout: kind::name. ``table::<name>`` entries hold each table
# in LOGICAL id order with padding rows stripped (``(num_ids, dim)``) —
# a served row lookup is therefore a plain axis-0 index, no owner-major
# physical mapping needed. ``ls::<i>`` entries are the flattened
# worker-local-state leaves (the Trainer path writes them in the logic's
# worker-count-independent EXPORT form, e.g. MF user factors in logical
# user order — exactly what a serving user-side lookup wants).
SEP = "::"
TABLE_PREFIX = f"table{SEP}"
LS_PREFIX = f"ls{SEP}"
# ``fold::<name>`` entries hold a table's hot-fold optimizer state
# (Adagrad/Adam server state, ``ServerLogic.hot_fold``) in reduce-scatter
# slice order — NEVER part of the canonical ``table::`` bytes, so a
# snapshot stays restorable by untiered/older readers (which simply skip
# the kind, as the default ``map_snapshot_arrays`` filter does).
FOLD_PREFIX = f"fold{SEP}"
CRC_PREFIX = f"meta{SEP}crc{SEP}"
# ``meta::mesh_shape`` records the (data, shard) mesh shape the snapshot
# was taken on (a JSON object) — restore detects a mesh-shape change and
# takes (and asserts) the explicit elastic re-split path. Pre-existing
# snapshots simply lack the tag.
MESH_SHAPE_KEY = f"meta{SEP}mesh_shape"
# ``meta::pod_epoch`` stamps the pod fencing epoch of the writer (pod
# runs only): forensic evidence that no epoch-stale publish ever landed
# behind a fence.
POD_EPOCH_KEY = f"meta{SEP}pod_epoch"
# Delta entry layout: a delta publication carries, for each row-sparse
# full-form key ``K`` (``table::name`` / ``ls::i`` / ``fold::name``), the
# pair ``dids::K`` (sorted int64 row ids) and ``drows::K`` (the touched
# rows' values). A key appearing under its PLAIN name inside a delta is a
# full replacement (shape/dtype changed, or a non-row-sparse leaf); a key
# absent entirely is carried unchanged from the base. ``meta::base_step``
# names the publication this delta chains from.
BASE_STEP_KEY = f"meta{SEP}base_step"
DELTA_IDS_PREFIX = f"dids{SEP}"
DELTA_ROWS_PREFIX = f"drows{SEP}"
# verify_snapshot_file's reason string for a vanished candidate — the
# poll-loop race (swept/renamed between stat and open) must be treated
# as "gone, retry next poll", never as corruption.
NO_SUCH_FILE = "no such file"


class ChainError(Exception):
    """A delta chain cannot be resolved (missing/broken/stale link).

    ``step`` names the FAILING link — everything chained past it is
    unrecoverable; everything before it is the surviving prefix."""

    def __init__(self, msg: str, *, step: int | None = None):
        super().__init__(msg)
        self.step = step

# Everything a torn/corrupted .npz throws on open or member read (zip
# magic, central directory, member CRC, npy header parsing, ...).
# Deliberately NOT OSError: transient environment failures (EMFILE,
# EACCES, a flaky NFS mount) must surface as what they are, not be
# classified as corruption.
IO_ERRORS = (
    EOFError,
    KeyError,
    IndexError,
    ValueError,
    struct.error,
    zipfile.BadZipFile,
    zipfile.LargeZipFile,
    zlib.error,
)


# Hostile-filesystem read seam: the deterministic injector may fail a
# read (transient ENOENT / EIO raise here) or redirect it to the
# PRE-rename content of the path — the stale read-after-rename of a
# caching network filesystem. Identity (and zero-cost) with no injector
# installed. One shared helper (fps_tpu.core.retry.read_path) so the
# checkpoint / snapshot-format / fleet read sites cannot drift.
_stale_read_seam = _retry.read_path


def array_crc32(arr) -> int:
    """CRC-32 of an array's raw bytes (dtype+shape-independent payload
    checksum; shapes/dtypes are validated by the restore paths' spec
    checks). Zero-copy: crc32 consumes the array's buffer directly."""
    a = np.asarray(arr)
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
    return zlib.crc32(a)


def snapshot_path(directory: str, step: int) -> str:
    return os.path.join(directory, SNAPSHOT_FMT.format(step=step))


def snapshot_steps(directory: str) -> list[int]:
    """Published snapshot steps under ``directory``, ascending. Missing
    directory reads as empty (a watcher may start before the trainer's
    first save)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for f in names:
        m = SNAPSHOT_RE.fullmatch(f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def delta_path(directory: str, step: int, base: int) -> str:
    return os.path.join(directory, DELTA_FMT.format(step=step, base=base))


class Publication:
    """One discovered publication: a full snapshot or a delta link."""

    __slots__ = ("step", "kind", "base", "path")

    def __init__(self, step: int, kind: str, base: int | None, path: str):
        self.step = step
        self.kind = kind  # "full" | "delta"
        self.base = base  # delta only: the step it chains from
        self.path = path

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Publication(step={self.step}, kind={self.kind!r}, "
                f"base={self.base}, path={self.path!r})")


def publications(directory: str) -> dict:
    """``{step: Publication}`` for every live publication under
    ``directory``. A full and a delta at the SAME step (the window while
    a background compaction's sweep hasn't finished) resolve to the full
    — the compactor's fold is bit-exact, so the two describe identical
    state and the standalone file wins. Missing directory reads empty."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return {}
    out: dict[int, Publication] = {}
    for f in names:
        m = DELTA_RE.fullmatch(f)
        if m:
            step = int(m.group(1))
            if step not in out:  # full-wins handled below (fulls override)
                out[step] = Publication(step, "delta", int(m.group(2)),
                                        os.path.join(directory, f))
    for f in names:
        m = SNAPSHOT_RE.fullmatch(f)
        if m:
            step = int(m.group(1))
            out[step] = Publication(step, "full", None,
                                    os.path.join(directory, f))
    return out


def chain_members(pubs: dict, step: int) -> list:
    """The back-chain of publication ``step`` as a base-FIRST list
    ``[full, delta, ..., head]``. Raises :class:`ChainError` (naming the
    failing link) when a base is missing — a quarantined (``*.corrupt``)
    base is simply absent from ``pubs``, so a chain through it is broken
    by construction."""
    head = pubs.get(step)
    if head is None:
        raise ChainError(f"no publication at step {step}", step=step)
    members = [head]
    seen = {step}
    cur = head
    while cur.kind == "delta":
        nxt = pubs.get(cur.base)
        if nxt is None:
            raise ChainError(
                f"delta step {cur.step} chains from step {cur.base}, "
                "which has no live publication (swept, quarantined, or "
                "never landed)", step=cur.step)
        if nxt.step in seen or nxt.step >= cur.step:
            raise ChainError(
                f"delta step {cur.step} has a non-monotone base "
                f"{cur.base}", step=cur.step)
        seen.add(nxt.step)
        members.append(nxt)
        cur = nxt
    members.reverse()
    return members


def read_pub_meta(path: str) -> dict:
    """``{"base_step": int|None, "pod_epoch": int|None}`` of one
    publication, via numpy's lazy member access (only these entries'
    bytes are read). Structural failures surface as the usual torn-file
    errors — callers verifying chains treat them as a failing link."""
    out = {"base_step": None, "pod_epoch": None}
    path = _stale_read_seam(path)
    with np.load(path) as z:
        if BASE_STEP_KEY in z.files:
            out["base_step"] = int(z[BASE_STEP_KEY])
        if POD_EPOCH_KEY in z.files:
            out["pod_epoch"] = int(z[POD_EPOCH_KEY])
    return out


def _check_chain_meta(members: list) -> tuple[bool, str | None, int | None]:
    """Cross-check each link's CRC-tagged ``meta::base_step`` against the
    filename chain and enforce fencing-epoch MONOTONICITY base→head: a
    delta carrying an epoch OLDER than an earlier link's is a stale
    zombie's publish that must truncate the chain there (the read-side
    half of the pod fence). Returns ``(ok, reason, failing_step)``."""
    max_epoch = None
    prev_step = None
    for pub in members:
        try:
            meta = read_pub_meta(pub.path)
        except FileNotFoundError:
            return False, NO_SUCH_FILE, pub.step
        except IO_ERRORS as e:
            return False, f"unreadable: {e!r}", pub.step
        if pub.kind == "delta":
            if meta["base_step"] is None or meta["base_step"] != pub.base:
                return (False,
                        f"delta step {pub.step}: meta::base_step "
                        f"{meta['base_step']} != filename base {pub.base}",
                        pub.step)
            if prev_step is not None and pub.base != prev_step:
                return (False,
                        f"delta step {pub.step} chains from {pub.base}, "
                        f"not the previous link {prev_step}", pub.step)
        epoch = meta["pod_epoch"]
        if epoch is not None:
            if max_epoch is not None and epoch < max_epoch:
                return (False,
                        f"step {pub.step}: fencing epoch {epoch} is "
                        f"behind an earlier link's epoch {max_epoch} — "
                        "stale-zombie publish", pub.step)
            max_epoch = epoch if max_epoch is None else max(max_epoch,
                                                            epoch)
        prev_step = pub.step
    return True, None, None


def verify_chain(directory: str, step: int, *, pubs: dict | None = None
                 ) -> tuple[bool, str | None, int | None]:
    """Full integrity pass over the whole chain ending at ``step``:
    every link exists, CRC-verifies, cross-links correctly, and carries
    a monotone fencing epoch. Returns ``(ok, reason, failing_step)`` —
    read-only and exception-free, like :func:`verify_snapshot_file`."""
    if pubs is None:
        pubs = publications(directory)
    try:
        members = chain_members(pubs, step)
    except ChainError as e:
        return False, str(e), e.step
    for pub in members:
        ok, reason = verify_snapshot_file(pub.path)
        if not ok:
            return False, f"step {pub.step}: {reason}", pub.step
    return _check_chain_meta(members)


def latest_valid_chain(directory: str) -> tuple[int, list] | None:
    """Newest ``(step, chain_members)`` whose whole chain passes
    :func:`verify_chain`, scanning newest→oldest; ``None`` when none
    does. The chain-aware twin of :func:`latest_valid_snapshot` — a
    torn/CRC-failing/epoch-stale link truncates eligibility back to the
    last verified prefix (its own head steps are still candidates)."""
    pubs = publications(directory)
    for step in sorted(pubs, reverse=True):
        ok, _, _ = verify_chain(directory, step, pubs=pubs)
        if ok:
            return step, chain_members(pubs, step)
    return None


def read_delta_arrays(path: str) -> dict:
    """All non-CRC entries of one delta publication, materialized (a
    delta is O(touched rows) by construction — mapping buys nothing)."""
    path = _stale_read_seam(path)
    with np.load(path) as z:
        return {k: z[k] for k in z.files if not k.startswith(CRC_PREFIX)}


def apply_delta_entries(entries: dict, delta: dict) -> dict:
    """Overlay one delta's entries onto a full-form ``entries`` dict
    (``{key: array}`` in the full snapshot's key layout). Sparse pairs
    patch rows copy-on-write; plain keys replace; ``meta::base_step``
    never propagates (the result is full-form state, not a link)."""
    out = dict(entries)
    for k, v in delta.items():
        if k.startswith(DELTA_IDS_PREFIX) or k == BASE_STEP_KEY:
            continue
        if k.startswith(DELTA_ROWS_PREFIX):
            key = k[len(DELTA_ROWS_PREFIX):]
            ids = np.asarray(delta[DELTA_IDS_PREFIX + key], np.int64)
            if key not in out:
                raise ChainError(
                    f"delta patches {key!r}, absent from the base")
            arr = np.array(out[key], copy=True)
            if len(ids) and (ids.min() < 0 or ids.max() >= len(arr)):
                raise ChainError(
                    f"delta row ids out of range for {key!r}")
            arr[ids] = v
            out[key] = arr
        else:
            out[k] = v
    return out


def resolve_chain_entries(members: list) -> dict:
    """Materialize the full-form state described by a chain (base-first
    :class:`Publication` list): load the full, then fold every delta in
    order. Integrity is the caller's job (:func:`verify_chain` first)."""
    base = members[0]
    with np.load(base.path) as z:
        entries = {k: z[k] for k in z.files if not k.startswith(CRC_PREFIX)}
    for pub in members[1:]:
        entries = apply_delta_entries(entries, read_delta_arrays(pub.path))
    entries.pop(BASE_STEP_KEY, None)
    return entries


def verify_snapshot_file(path: str) -> tuple[bool, str | None]:
    """Full integrity pass over one snapshot file: ``(True, None)`` iff
    every entry reads back and matches its ``meta::crc`` tag; otherwise
    ``(False, reason)``. Pre-integrity snapshots (no crc tags) still get
    the structural checks — an unreadable zip fails either way.

    Read-only and exception-free on corruption (unlike the checkpoint
    layer's restore path, which quarantines): a serving process must be
    able to reject a bad publish without mutating the training plane's
    directory.
    """
    try:
        path = _stale_read_seam(path)
        with np.load(path) as z:
            for k in z.files:
                if k.startswith(CRC_PREFIX):
                    continue
                v = z[k]
                ck = CRC_PREFIX + k
                if ck in z.files and int(z[ck]) != array_crc32(v):
                    return False, f"checksum mismatch on entry {k!r}"
    except FileNotFoundError:
        return False, NO_SUCH_FILE
    except IO_ERRORS as e:
        return False, f"unreadable: {e!r}"
    return True, None


def latest_valid_snapshot(directory: str) -> tuple[int, str] | None:
    """Newest ``(step, path)`` whose snapshot passes
    :func:`verify_snapshot_file`, scanning newest→oldest; ``None`` when
    none does. Read-only (corrupt files are left in place — the training
    plane's restore path owns quarantine)."""
    for step in reversed(snapshot_steps(directory)):
        path = snapshot_path(directory, step)
        ok, _ = verify_snapshot_file(path)
        if ok:
            return step, path
    return None


# ---------------------------------------------------------------------------
# Zero-copy member mapping.
# ---------------------------------------------------------------------------

def _member_data_offset(f, zinfo) -> int:
    """File offset of ``zinfo``'s raw data: past the LOCAL header, whose
    name/extra lengths can differ from the central directory's (zip64
    padding), so the local record must be parsed, not assumed."""
    f.seek(zinfo.header_offset)
    hdr = f.read(30)
    if len(hdr) != 30 or hdr[:4] != b"PK\x03\x04":
        raise ValueError(
            f"member {zinfo.filename!r}: bad local file header")
    nlen, elen = struct.unpack("<HH", hdr[26:30])
    return zinfo.header_offset + 30 + nlen + elen


def _read_npy_header(f):
    """``(dtype, shape, fortran_order, data_offset_from_current)`` of the
    npy stream at ``f``'s current position (format versions 1/2/3)."""
    fmt = np.lib.format
    version = fmt.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = fmt.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, fortran, dtype = fmt.read_array_header_2_0(f)
    else:  # a future 3.x header parses like 2.0 (utf-8 header text)
        shape, fortran, dtype = fmt.read_array_header_2_0(f)
    return dtype, shape, fortran


def map_snapshot_arrays(path: str, *, keys=None) -> dict[str, np.ndarray]:
    """Read-only zero-copy views of a snapshot's array entries.

    Returns ``{key: array}`` where each array is an ``np.memmap``
    (``mode="r"``) straight onto the member's bytes inside the ``.npz``
    — no decompression (``np.savez`` stores uncompressed), no copy, no
    resident memory until rows are touched. ``keys`` optionally
    restricts which entries are mapped (default: every ``table::`` and
    ``ls::`` entry; ``meta::*`` tags are never mapped — they are read by
    :func:`verify_snapshot_file`).

    The maps stay valid as long as the FILE CONTENT at ``path``'s inode
    survives; the checkpoint writer only ever publishes via atomic
    rename (a new inode), so a mapped snapshot can never change under a
    reader — deletion unlinks the name but the mapping keeps the pages.
    Integrity is the caller's job (``verify_snapshot_file`` first): a
    torn file fails verification before anything is mapped.

    Raises ``ValueError`` for members this scheme cannot map (compressed
    members, object dtypes, pickled entries) — none of which the
    checkpoint writer produces.
    """
    out: dict[str, np.ndarray] = {}
    path = _stale_read_seam(path)
    with zipfile.ZipFile(path) as zf, open(path, "rb") as f:
        for zinfo in zf.infolist():
            name = zinfo.filename
            key = name[:-4] if name.endswith(".npy") else name
            if keys is not None:
                if key not in keys:
                    continue
            elif not (key.startswith(TABLE_PREFIX)
                      or key.startswith(LS_PREFIX)):
                continue
            if zinfo.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"member {name!r} is compressed — zero-copy mapping "
                    "needs np.savez (stored), not savez_compressed")
            data_off = _member_data_offset(f, zinfo)
            f.seek(data_off)
            dtype, shape, fortran = _read_npy_header(f)
            if dtype.hasobject:
                raise ValueError(
                    f"member {name!r} holds object dtype — not mappable")
            out[key] = np.memmap(
                path, dtype=dtype, mode="r", offset=f.tell(), shape=shape,
                order="F" if fortran else "C",
            )
    return out
