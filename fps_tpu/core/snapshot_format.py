"""The on-disk snapshot contract — naming, integrity, zero-copy reads.

One jax-FREE module (stdlib + numpy) owning everything three consumers
must agree on about a published ``ckpt_*.npz`` snapshot:

* the **training plane** (:mod:`fps_tpu.core.checkpoint`) writes
  snapshots and restores them (it re-exports the names below, so nothing
  upstream changed);
* the **chaos injectors** (:mod:`fps_tpu.testing.chaos`) corrupt them by
  the same filename contract;
* the **serving plane** (:mod:`fps_tpu.serve`) — a jax-optional process
  that must discover, CRC-verify, and map snapshots on a machine that
  may not even have an accelerator runtime installed. Putting the
  contract here (instead of importing the jax-laden checkpoint module)
  is what makes that possible.

Integrity is the checkpoint layer's scheme verbatim: every array entry
``k`` carries a ``meta::crc::k`` CRC-32 tag written at save time;
:func:`verify_snapshot_file` checks every entry the way
``Checkpointer._read_verified`` does (structural read errors and
checksum mismatches both fail), but reports ``(ok, reason)`` instead of
raising the jax-layer's ``SnapshotCorruptionError``.

Zero-copy reads: ``np.savez`` writes an UNCOMPRESSED zip of ``.npy``
members, so each array's bytes sit contiguously at a knowable file
offset. :func:`map_snapshot_arrays` parses the zip's local headers plus
each member's npy header and returns read-only ``np.memmap`` views — a
multi-GB table "loads" in microseconds and costs no resident memory
until rows are touched. This is what makes a serving hot-swap a pointer
flip whose latency is independent of table size.
"""

from __future__ import annotations

import os
import re
import struct
import zipfile
import zlib

import numpy as np

__all__ = [
    "SNAPSHOT_RE", "SNAPSHOT_FMT", "SEP", "TABLE_PREFIX", "LS_PREFIX",
    "FOLD_PREFIX", "MESH_SHAPE_KEY", "POD_EPOCH_KEY",
    "CRC_PREFIX", "IO_ERRORS", "array_crc32", "snapshot_path",
    "snapshot_steps", "verify_snapshot_file", "latest_valid_snapshot",
    "map_snapshot_arrays",
]

# Snapshot filename contract — the single source of truth (the
# checkpoint layer and the chaos injectors import these from here or via
# fps_tpu.core.checkpoint's re-export).
SNAPSHOT_RE = re.compile(r"ckpt_(\d{12})\.npz")
SNAPSHOT_FMT = "ckpt_{step:012d}.npz"

# npz key layout: kind::name. ``table::<name>`` entries hold each table
# in LOGICAL id order with padding rows stripped (``(num_ids, dim)``) —
# a served row lookup is therefore a plain axis-0 index, no owner-major
# physical mapping needed. ``ls::<i>`` entries are the flattened
# worker-local-state leaves (the Trainer path writes them in the logic's
# worker-count-independent EXPORT form, e.g. MF user factors in logical
# user order — exactly what a serving user-side lookup wants).
SEP = "::"
TABLE_PREFIX = f"table{SEP}"
LS_PREFIX = f"ls{SEP}"
# ``fold::<name>`` entries hold a table's hot-fold optimizer state
# (Adagrad/Adam server state, ``ServerLogic.hot_fold``) in reduce-scatter
# slice order — NEVER part of the canonical ``table::`` bytes, so a
# snapshot stays restorable by untiered/older readers (which simply skip
# the kind, as the default ``map_snapshot_arrays`` filter does).
FOLD_PREFIX = f"fold{SEP}"
CRC_PREFIX = f"meta{SEP}crc{SEP}"
# ``meta::mesh_shape`` records the (data, shard) mesh shape the snapshot
# was taken on (a JSON object) — restore detects a mesh-shape change and
# takes (and asserts) the explicit elastic re-split path. Pre-existing
# snapshots simply lack the tag.
MESH_SHAPE_KEY = f"meta{SEP}mesh_shape"
# ``meta::pod_epoch`` stamps the pod fencing epoch of the writer (pod
# runs only): forensic evidence that no epoch-stale publish ever landed
# behind a fence.
POD_EPOCH_KEY = f"meta{SEP}pod_epoch"

# Everything a torn/corrupted .npz throws on open or member read (zip
# magic, central directory, member CRC, npy header parsing, ...).
# Deliberately NOT OSError: transient environment failures (EMFILE,
# EACCES, a flaky NFS mount) must surface as what they are, not be
# classified as corruption.
IO_ERRORS = (
    EOFError,
    KeyError,
    IndexError,
    ValueError,
    struct.error,
    zipfile.BadZipFile,
    zipfile.LargeZipFile,
    zlib.error,
)


def array_crc32(arr) -> int:
    """CRC-32 of an array's raw bytes (dtype+shape-independent payload
    checksum; shapes/dtypes are validated by the restore paths' spec
    checks). Zero-copy: crc32 consumes the array's buffer directly."""
    a = np.asarray(arr)
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
    return zlib.crc32(a)


def snapshot_path(directory: str, step: int) -> str:
    return os.path.join(directory, SNAPSHOT_FMT.format(step=step))


def snapshot_steps(directory: str) -> list[int]:
    """Published snapshot steps under ``directory``, ascending. Missing
    directory reads as empty (a watcher may start before the trainer's
    first save)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for f in names:
        m = SNAPSHOT_RE.fullmatch(f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def verify_snapshot_file(path: str) -> tuple[bool, str | None]:
    """Full integrity pass over one snapshot file: ``(True, None)`` iff
    every entry reads back and matches its ``meta::crc`` tag; otherwise
    ``(False, reason)``. Pre-integrity snapshots (no crc tags) still get
    the structural checks — an unreadable zip fails either way.

    Read-only and exception-free on corruption (unlike the checkpoint
    layer's restore path, which quarantines): a serving process must be
    able to reject a bad publish without mutating the training plane's
    directory.
    """
    try:
        with np.load(path) as z:
            for k in z.files:
                if k.startswith(CRC_PREFIX):
                    continue
                v = z[k]
                ck = CRC_PREFIX + k
                if ck in z.files and int(z[ck]) != array_crc32(v):
                    return False, f"checksum mismatch on entry {k!r}"
    except FileNotFoundError:
        return False, "no such file"
    except IO_ERRORS as e:
        return False, f"unreadable: {e!r}"
    return True, None


def latest_valid_snapshot(directory: str) -> tuple[int, str] | None:
    """Newest ``(step, path)`` whose snapshot passes
    :func:`verify_snapshot_file`, scanning newest→oldest; ``None`` when
    none does. Read-only (corrupt files are left in place — the training
    plane's restore path owns quarantine)."""
    for step in reversed(snapshot_steps(directory)):
        path = snapshot_path(directory, step)
        ok, _ = verify_snapshot_file(path)
        if ok:
            return step, path
    return None


# ---------------------------------------------------------------------------
# Zero-copy member mapping.
# ---------------------------------------------------------------------------

def _member_data_offset(f, zinfo) -> int:
    """File offset of ``zinfo``'s raw data: past the LOCAL header, whose
    name/extra lengths can differ from the central directory's (zip64
    padding), so the local record must be parsed, not assumed."""
    f.seek(zinfo.header_offset)
    hdr = f.read(30)
    if len(hdr) != 30 or hdr[:4] != b"PK\x03\x04":
        raise ValueError(
            f"member {zinfo.filename!r}: bad local file header")
    nlen, elen = struct.unpack("<HH", hdr[26:30])
    return zinfo.header_offset + 30 + nlen + elen


def _read_npy_header(f):
    """``(dtype, shape, fortran_order, data_offset_from_current)`` of the
    npy stream at ``f``'s current position (format versions 1/2/3)."""
    fmt = np.lib.format
    version = fmt.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = fmt.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, fortran, dtype = fmt.read_array_header_2_0(f)
    else:  # a future 3.x header parses like 2.0 (utf-8 header text)
        shape, fortran, dtype = fmt.read_array_header_2_0(f)
    return dtype, shape, fortran


def map_snapshot_arrays(path: str, *, keys=None) -> dict[str, np.ndarray]:
    """Read-only zero-copy views of a snapshot's array entries.

    Returns ``{key: array}`` where each array is an ``np.memmap``
    (``mode="r"``) straight onto the member's bytes inside the ``.npz``
    — no decompression (``np.savez`` stores uncompressed), no copy, no
    resident memory until rows are touched. ``keys`` optionally
    restricts which entries are mapped (default: every ``table::`` and
    ``ls::`` entry; ``meta::*`` tags are never mapped — they are read by
    :func:`verify_snapshot_file`).

    The maps stay valid as long as the FILE CONTENT at ``path``'s inode
    survives; the checkpoint writer only ever publishes via atomic
    rename (a new inode), so a mapped snapshot can never change under a
    reader — deletion unlinks the name but the mapping keeps the pages.
    Integrity is the caller's job (``verify_snapshot_file`` first): a
    torn file fails verification before anything is mapped.

    Raises ``ValueError`` for members this scheme cannot map (compressed
    members, object dtypes, pickled entries) — none of which the
    checkpoint writer produces.
    """
    out: dict[str, np.ndarray] = {}
    with zipfile.ZipFile(path) as zf, open(path, "rb") as f:
        for zinfo in zf.infolist():
            name = zinfo.filename
            key = name[:-4] if name.endswith(".npy") else name
            if keys is not None:
                if key not in keys:
                    continue
            elif not (key.startswith(TABLE_PREFIX)
                      or key.startswith(LS_PREFIX)):
                continue
            if zinfo.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"member {name!r} is compressed — zero-copy mapping "
                    "needs np.savez (stored), not savez_compressed")
            data_off = _member_data_offset(f, zinfo)
            f.seek(data_off)
            dtype, shape, fortran = _read_npy_header(f)
            if dtype.hasobject:
                raise ValueError(
                    f"member {name!r} holds object dtype — not mappable")
            out[key] = np.memmap(
                path, dtype=dtype, mode="r", offset=f.tell(), shape=shape,
                order="F" if fortran else "C",
            )
    return out
