"""fps_tpu.analysis — the program contract auditor.

Static analysis over what the framework actually *compiles*, at two
altitudes (see ``docs/analysis.md``):

* **HLO passes** — :class:`HloProgram` parses a lowered StableHLO module
  (ops, payload bytes, replica groups, donation markers) and the pass
  suite (:mod:`fps_tpu.analysis.passes`) certifies it against a
  :class:`ProgramContract`: collective count/byte budgets per kind, no
  host transfers inside the step, canonical tables donated in-place, no
  dtype drift, and the hot-tier reconcile psum present when tiering is
  on. ``Trainer(audit=...)`` certifies every program it compiles;
  ``tools/audit_programs.py`` certifies the example workloads and writes
  the certificate JSON.
* **AST linter** — :mod:`fps_tpu.analysis.lint` catches the jax-specific
  source hazards that produce wrong programs (late-bound closures over
  loop variables, bool branches on tracers, unsorted dict iteration in
  compiled-fn builders, unsynchronized thread state, shim indirection);
  ``tools/lint.py`` runs it over the package and a tier-1 test keeps it
  at zero findings.

Pure host-side: the analysis modules themselves never import jax (they
parse text and source), so the tools work against saved ``.as_text()``
dumps. On a jax-free login node, don't import this package directly
(``fps_tpu/__init__`` imports jax) — ``tools/lint.py`` loads the linter
by file path (the ``tools/supervise.py`` pattern) and
``tools/audit_programs.py --hlo DUMP.txt`` loads the HLO layer through
a stub root package, so both CLIs run without jax.
"""

from fps_tpu.analysis.contract import (
    Certificate,
    ContractViolationError,
    ProgramAuditor,
    ProgramContract,
    Violation,
    as_auditor,
    certify,
    contract_for_trainer,
)
from fps_tpu.analysis.hlo import (
    Collective,
    HloOp,
    HloProgram,
    collective_profile,
    count_collectives,
)
from fps_tpu.analysis.lint import LintFinding, lint_paths, lint_source
from fps_tpu.analysis.passes import (
    DEFAULT_PASSES,
    AnalysisPass,
    CollectiveBudget,
    DonationAudit,
    DtypeDriftDetector,
    HostTransferDetector,
    ReplicaConsistency,
)

__all__ = [
    "HloProgram", "HloOp", "Collective",
    "collective_profile", "count_collectives",
    "ProgramContract", "Violation", "Certificate",
    "ContractViolationError", "ProgramAuditor", "as_auditor",
    "certify", "contract_for_trainer",
    "AnalysisPass", "CollectiveBudget", "HostTransferDetector",
    "DonationAudit", "DtypeDriftDetector", "ReplicaConsistency",
    "DEFAULT_PASSES",
    "LintFinding", "lint_source", "lint_paths",
]
