"""jax-hazard source linter — the AST layer of the contract auditor.

The HLO passes certify what a program *lowered to*; this linter catches
the Python-side hazards that produce wrong programs in the first place —
each rule encodes a failure mode this codebase has hand-dodged (and in
some cases shipped and fixed) before:

* **FPS001 jit-closure-loop-var** — a closure defined inside a loop that
  reads the loop variable late-binds it: every traced program sees the
  LAST iteration's value (the classic "all my compiled fns use the same
  table" bug). Bind it as a default argument (``lambda x, _v=v: ...``).
* **FPS002 tracer-bool-context** — ``if jnp.any(...)`` / ``while
  jnp.all(...)``: under tracing this raises TracerBoolConversionError;
  on host values it silently forces a device sync per call. Use
  ``lax.cond``/``jnp.where`` in traced code, ``np.*`` on host.
* **FPS003 unsorted-traced-items** — dict iteration feeding tree
  construction inside a compiled-fn builder (lexically within a
  function whose subtree calls ``lax.scan`` / ``lax.fori_loop`` /
  ``lax.while_loop`` / ``shard_map``). Insertion-order iteration bakes
  dict construction *history* into the traced program — two processes
  (or two code paths) that built the dict differently trace different
  programs, the multi-controller determinism hazard. Iterate
  ``sorted(d.items())``.
* **FPS004 thread-shared-state** — a class that starts a
  ``threading.Thread``/``Timer`` without any synchronization primitive
  (Lock/Condition/Event/Queue/...) or an explicit ``thread-safety:``
  note in its docstring. Prefetch/checkpoint-style background workers
  sharing mutable state without a documented discipline is how torn
  snapshots happen.
* **FPS005 internal-shim-import** — importing the
  ``fps_tpu.utils.profiling`` compat shim from inside the package.
  Shims exist for EXTERNAL callers; internal indirection through a
  deprecated alias hides the real dependency edge.
* **FPS006 raw-snapshot-read** — ``open()`` / ``np.load`` of a
  checkpoint/snapshot-flavored path outside the sanctioned readers
  (``core/checkpoint.py``, ``core/snapshot_format.py``, ``serve/``).
  Every snapshot read must go through the CRC-verified paths — a raw
  ``np.load`` of a ``ckpt_*.npz`` silently accepts a torn or bit-rotted
  file the integrity layer exists to reject.
* **FPS007 host-clock-in-builder** — ``time.time()`` /
  ``time.perf_counter()`` (and friends) inside a compiled-fn builder
  subtree (the FPS003 scope). A host clock read while TRACING runs once
  at trace time and bakes a constant into the program — it measures
  nothing, and two traces of the "same" program differ. Host timing
  belongs in ``PhaseTimer`` (``fps_tpu.obs.timing``), outside the
  builders; device timing belongs to the profiler.
* **FPS008 raw-socket-use** — ``socket.socket()`` /
  ``socket.create_connection()`` outside ``fps_tpu/serve/`` (where the
  framed wire layer lives). A raw socket dodges the per-request
  deadlines, classified bounded retry, and request-id dedupe the
  hostile-network model guarantees — one naked ``recv`` against a
  partitioned peer wedges its caller forever. Speak
  ``fps_tpu.serve.wire.WireClient``.
* **FPS009 raw-tenant-path** — a path call whose arguments spell a
  tenant-namespace literal (``"tenants"`` / ``"tenant.json"``) outside
  the sanctioned helper (``fps_tpu/tenancy/paths.py``). Tenant
  blast-radius isolation is a PATH property: every checkpoint/obs/
  sidecar file must live under ``<root>/tenants/<name>/...``, and the
  namespace audit only holds if every plane derives those paths from
  ``TenantPaths`` (or, in stdlib-only login-node tools, from a mirrored
  ``TENANTS_DIRNAME`` constant — a Name, which this rule deliberately
  does not flag). A hand-spelled ``"tenants"`` literal is one typo away
  from writing into a neighbor's namespace.

* **FPS011 blocking-host-work-on-training-thread** — ``time.sleep`` /
  ``os.fsync`` / ``jax.device_get`` / ``.block_until_ready`` in the
  training-thread scope (``core/driver.py`` / ``core/megastep.py``).
  The raw-speed contract: a save costs the training thread one enqueue
  of on-device boundary copies, a degraded publish one counter bump —
  capture, fsync, and retry backoff run on the checkpoint writer and
  background retrier threads (the calibration window's forced syncs
  live in ``core/autok.py``, outside the scope).

Suppression: append ``# noqa: FPSNNN`` to the flagged line — but the
tier-1 test runs this linter over ``fps_tpu/`` expecting zero findings,
so in-tree fixes are the norm, suppressions the exception.

Stdlib-only (ast + tokenize-free): safe anywhere, no jax import.
"""

from __future__ import annotations

import ast
import dataclasses
import os

__all__ = ["LintFinding", "RULES", "lint_source", "lint_paths",
           "iter_py_files"]


@dataclasses.dataclass(frozen=True)
class LintFinding:
    rule: str
    path: str
    line: int
    message: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


# Rule id -> one-line rationale (the CLI's --explain output).
RULES = {
    "FPS001": "closure in a loop late-binds the loop variable — bind it "
              "as a default argument",
    "FPS002": "boolean branch on a jnp predicate — TracerBoolConversion "
              "under jit, a hidden device sync on host",
    "FPS003": "unsorted dict iteration building a tree inside a "
              "compiled-fn builder — iterate sorted(d.items())",
    "FPS004": "class starts a thread but declares no synchronization "
              "primitive or thread-safety note",
    "FPS005": "internal import of the fps_tpu.utils.profiling shim — "
              "import from fps_tpu.obs",
    "FPS006": "raw open()/np.load of a checkpoint/snapshot path outside "
              "the CRC-verified readers (core/checkpoint.py, "
              "core/snapshot_format.py, serve/)",
    "FPS007": "host clock call (time.time/perf_counter/...) inside a "
              "compiled-fn builder — it bakes a trace-time constant "
              "into the program; host timing stays in PhaseTimer",
    "FPS008": "raw socket use outside fps_tpu/serve/ — every caller "
              "goes through the framed WireClient (deadlines, bounded "
              "retry, idempotent reconnect)",
    "FPS009": "hand-spelled tenant-namespace literal in a path call "
              "outside fps_tpu/tenancy/paths.py — derive tenant paths "
              "from TenantPaths (or a mirrored *_DIRNAME constant)",
    "FPS010": "whole-table materialization (np.asarray/np.array/"
              ".copy()) of a snapshot table view in the serve hot path "
              "— answer off the mapped pages / DeltaView, or go "
              "through the sanctioned materialize() seam",
    "FPS011": "blocking host work (time.sleep/os.fsync/jax.device_get/"
              ".block_until_ready) in the training-thread scope of "
              "core/driver.py or core/megastep.py — capture, fsync, "
              "and retry backoff belong on the checkpoint writer / "
              "background retrier threads",
}

# Calls whose presence makes a function (and everything lexically inside
# it) a compiled-fn builder for FPS003/FPS007.
_TRACE_TRIGGERS = {"scan", "fori_loop", "while_loop", "shard_map"}

# FPS007: host wall-clock reads that are trace-time constants inside a
# compiled-fn builder. Bare names cover `from time import perf_counter`
# — including bare `time` itself (`from time import time; time()`),
# which can false-positive on a user callable named `time` inside a
# builder; rename it or `# noqa: FPS007`.
_HOST_CLOCKS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.thread_time",
    "time", "perf_counter", "monotonic", "process_time", "thread_time",
}

# jnp predicates that return arrays — poison in a bool context.
_TRACER_PREDICATES = {
    "any", "all", "isnan", "isinf", "isfinite", "array_equal", "allclose",
    "logical_and", "logical_or", "logical_not", "equal", "not_equal",
    "less", "less_equal", "greater", "greater_equal",
}

# FPS006: name/attribute/string tokens marking an expression as
# checkpoint-flavored, and the files sanctioned to read snapshots raw
# (they ARE the verified readers / the on-disk-contract owner).
_CKPT_TOKENS = ("ckpt", "snapshot")
_CKPT_READER_PATHS = ("fps_tpu/core/checkpoint.py",
                      "fps_tpu/core/snapshot_format.py")
_CKPT_READER_DIRS = ("fps_tpu/serve/",)

# FPS008: raw socket constructors; only the wire/net modules under
# fps_tpu/serve/ may call them — everything else speaks the framed
# protocol through WireClient (docs/serving.md). Both the dotted and
# the `from socket import ...` bare forms are flagged.
_RAW_SOCKET_CALLS = {
    "socket.socket", "socket.create_connection", "create_connection",
}
_SOCKET_OK_DIRS = ("fps_tpu/serve/",)

# FPS009: path-constructing calls whose STRING arguments may not spell
# the tenant namespace by hand; only the helper module owns the layout.
# Mirrored Name constants (TENANTS_DIRNAME) pass — the rule keys on
# string literals, the typo-prone form.
_TENANT_PATH_CALLS = {
    "open", "os.path.join", "path.join", "os.makedirs", "os.listdir",
    "os.path.isdir", "os.path.isfile", "os.path.exists", "os.remove",
    "os.rmdir", "glob.glob", "glob.iglob", "Path", "pathlib.Path",
    "shutil.rmtree", "shutil.copytree",
}
_TENANT_TOKENS = ("tenants", "tenant.json")
_TENANT_HELPER_PATHS = ("fps_tpu/tenancy/paths.py",)

# FPS010: the read plane's zero-copy contract (docs/serving.md
# "Read-plane throughput"): a snapshot table is a read-only-mmapped view
# (or a DeltaView overlay on one), and the serve hot path must answer
# off those pages — an np.asarray/np.array/.copy() of a TABLE there is
# an O(table) allocation per request, the exact regression the batched
# wire exists to kill. The ONE sanctioned densification seam is
# fps_tpu.serve.snapshot.materialize() (and the DeltaView.__array__ it
# rides), so functions by those names are exempt.
_FPS010_MATERIALIZERS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.ascontiguousarray", "numpy.ascontiguousarray",
}
_FPS010_ALLOW_FUNCS = {"__array__", "materialize"}
_FPS010_DIRS = ("fps_tpu/serve/",)

# FPS011: the raw-speed contract (docs/performance.md "The raw-speed
# pass"): nothing on the training thread may sleep, fsync, or force a
# device->host sync — a brownout's retry backoff or a snapshot capture
# landing here is exactly the host-serial share the deferred-capture /
# background-retrier seams exist to absorb. Scope is the two
# training-loop files; the sanctioned seams (the AsyncCheckpointer
# writer, the sidecar retrier, the auto-K calibration window in
# core/autok.py) live OUTSIDE them, so any new blocking call here is a
# regression, not a judgment call. Both dotted and `from x import y`
# bare forms are flagged.
_FPS011_BLOCKING_CALLS = {
    "time.sleep", "sleep", "os.fsync", "fsync",
    "jax.device_get", "device_get", "jax.block_until_ready",
}
_FPS011_PATHS = ("fps_tpu/core/driver.py", "fps_tpu/core/megastep.py")
# Functions that ARE a sanctioned off-thread seam, should one ever move
# into a scoped file (writer loops / background retriers run on their
# own threads — blocking there is the point).
_FPS011_ALLOW_FUNCS = {"_writer_loop", "_run_capture",
                       "_sidecar_retry_loop"}

_SYNC_PRIMITIVES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
}
_THREAD_STARTERS = {"Thread", "Timer"}


def _attr_chain(node) -> str:
    """Dotted name of an attribute/name chain ('' when not a chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _call_name(node) -> str:
    return _attr_chain(node.func) if isinstance(node, ast.Call) else ""


def _items_call(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("items", "keys", "values")
            and not node.args)


def _bound_names(fn) -> set[str]:
    """Names a closure binds itself: parameters (defaults included by
    construction — a default REBINDS the name at def time, which is the
    sanctioned fix) plus names assigned in its body."""
    out = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                out.add(n.id)
    return out


def _loop_target_names(node) -> set[str]:
    out = set()
    if isinstance(node, (ast.For, ast.AsyncFor)):
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: list[str]):
        self.path = path
        self.lines = source_lines
        self.findings: list[LintFinding] = []
        norm = path.replace(os.sep, "/")
        self.is_shim = norm.endswith("fps_tpu/utils/profiling.py")
        # FPS006 exemption: the sanctioned snapshot readers themselves.
        self.is_ckpt_reader = (
            any(norm.endswith(p) for p in _CKPT_READER_PATHS)
            or any(d in norm for d in _CKPT_READER_DIRS))
        # FPS008 exemption: the wire/net modules ARE the framed layer.
        self.is_wire_module = any(d in norm for d in _SOCKET_OK_DIRS)
        # FPS009 exemption: the tenant path helper owns the layout.
        self.is_tenant_helper = any(
            norm.endswith(p) for p in _TENANT_HELPER_PATHS)
        # FPS010 scope: only the serve hot path carries the zero-copy
        # contract; training/tools code materializes freely.
        self.is_serve_hot = any(d in norm for d in _FPS010_DIRS)
        # FPS011 scope: the training-thread files; depth of enclosing
        # sanctioned off-thread seams (writer loop / background
        # retrier defs).
        self.is_training_hot = any(
            norm.endswith(p) for p in _FPS011_PATHS)
        self._fps011_allow = 0
        # Names assigned from table-view expressions (filled by
        # visit_Module's dataflow pre-pass).
        self._table_names: set[str] = set()
        # Depth of enclosing materialize()/__array__ defs — the
        # sanctioned densification seam.
        self._fps010_allow = 0
        # FPS001: stack of (loop_node, target_names) we are inside of.
        self._loops: list[tuple[ast.AST, set[str]]] = []
        # FPS003: depth of enclosing compiled-fn-builder functions.
        self._trace_depth = 0

    # -- plumbing ---------------------------------------------------------

    def _add(self, rule: str, node, message: str) -> None:
        line = getattr(node, "lineno", 1)
        src = self.lines[line - 1] if line - 1 < len(self.lines) else ""
        if f"noqa: {rule}" in src:
            return
        self.findings.append(LintFinding(rule, self.path, line, message))

    # -- FPS005 -----------------------------------------------------------

    def visit_Import(self, node):
        if not self.is_shim:
            for alias in node.names:
                if alias.name == "fps_tpu.utils.profiling":
                    self._add("FPS005", node,
                              "import of the utils.profiling shim — use "
                              "fps_tpu.obs (trace/Throughput live there)")
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if not self.is_shim:
            mod = node.module or ""
            if mod == "fps_tpu.utils.profiling" or (
                    mod == "fps_tpu.utils"
                    and any(a.name == "profiling" for a in node.names)):
                self._add("FPS005", node,
                          "import of the utils.profiling shim — use "
                          "fps_tpu.obs (trace/Throughput live there)")
        self.generic_visit(node)

    # -- FPS006 -----------------------------------------------------------

    def _ckpt_flavored(self, node) -> bool:
        """Any name/attribute/string in the call's arguments carrying a
        checkpoint token — the heuristic that 'this path is a snapshot'."""
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for n in ast.walk(arg):
                text = ""
                if isinstance(n, ast.Name):
                    text = n.id
                elif isinstance(n, ast.Attribute):
                    text = n.attr
                elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                    text = n.value
                low = text.lower()
                if any(tok in low for tok in _CKPT_TOKENS):
                    return True
        return False

    def _tenant_flavored(self, node) -> bool:
        """A string literal in the call's arguments spelling the tenant
        namespace (``"tenants"`` path segment or the manifest name)."""
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for n in ast.walk(arg):
                if not (isinstance(n, ast.Constant)
                        and isinstance(n.value, str)):
                    continue
                low = n.value.lower()
                if ("tenant.json" in low or low == "tenants"
                        or "tenants/" in low
                        or low.endswith("/tenants")):
                    return True
        return False

    # -- FPS010 -----------------------------------------------------------

    def visit_Module(self, node):
        # Dataflow pre-pass: names assigned from table-view expressions
        # anywhere in the file (iterated to a fixpoint so one level of
        # aliasing — q = snap.table(n); r = q — still carries flavor).
        if self.is_serve_hot:
            for _ in range(4):  # bounded: alias chains are short
                grew = False
                for n in ast.walk(node):
                    if (isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Name)
                            and self._table_flavored(n.value)
                            and n.targets[0].id not in self._table_names):
                        self._table_names.add(n.targets[0].id)
                        grew = True
                if not grew:
                    break
        self.generic_visit(node)

    def _table_flavored(self, node) -> bool:
        """True for expressions that ARE a snapshot table view: a
        ``.table(...)`` accessor call, a ``.tables[...]`` subscript, a
        ``.base`` attribute (DeltaView's mapped base), or a name
        assigned from one. A SUBSCRIPT of a flavored expression is NOT
        flavored — ``table[ids]`` is the gather result (bounded by the
        request), and materializing it is the point."""
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            return bool(chain) and chain.split(".")[-1] == "table"
        if isinstance(node, ast.Subscript):
            chain = _attr_chain(node.value)
            return bool(chain) and chain.split(".")[-1] == "tables"
        if isinstance(node, ast.Attribute):
            return node.attr in ("base", "tables")
        if isinstance(node, ast.Name):
            return node.id in self._table_names
        return False

    def _check_fps010(self, node):
        if not self.is_serve_hot or self._fps010_allow:
            return
        name = _call_name(node)
        if (name in _FPS010_MATERIALIZERS and node.args
                and self._table_flavored(node.args[0])):
            self._add(
                "FPS010", node,
                f"{name}() of a snapshot table view in the serve hot "
                "path — an O(table) copy per request; answer off the "
                "mapped pages (fancy-index the view) or, when a dense "
                "whole table is genuinely needed, go through "
                "fps_tpu.serve.snapshot.materialize()")
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr == "copy" and not node.args
                and self._table_flavored(node.func.value)):
            self._add(
                "FPS010", node,
                ".copy() of a snapshot table view in the serve hot "
                "path — an O(table) copy per request; answer off the "
                "mapped pages or go through "
                "fps_tpu.serve.snapshot.materialize()")

    def _check_fps011(self, node):
        if not self.is_training_hot or self._fps011_allow:
            return
        name = _call_name(node)
        if name in _FPS011_BLOCKING_CALLS:
            self._add(
                "FPS011", node,
                f"{name}() on the training thread — sleeps, fsyncs, and "
                "forced device->host syncs are host-serial share; move "
                "them onto the checkpoint writer / background retrier "
                "(or core/autok.py's calibration window)")
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"):
            self._add(
                "FPS011", node,
                ".block_until_ready() on the training thread — a forced "
                "device->host sync serializes dispatch; adjudicate off "
                "host copies or move the sync to a background seam")

    def visit_Call(self, node):
        self._check_fps010(node)
        self._check_fps011(node)
        # FPS007: a host clock read under tracing is a constant, not a
        # measurement (the _trace_depth scope is FPS003's).
        if self._trace_depth and _call_name(node) in _HOST_CLOCKS:
            self._add(
                "FPS007", node,
                f"{_call_name(node)}() inside a compiled-fn builder — "
                "a host clock read at trace time bakes a constant into "
                "the program; host timing stays in PhaseTimer "
                "(fps_tpu.obs.timing), outside the builders")
        if not self.is_ckpt_reader:
            name = _call_name(node)
            if (name in ("open", "np.load", "numpy.load")
                    and self._ckpt_flavored(node)):
                self._add(
                    "FPS006", node,
                    f"{name}() of a checkpoint/snapshot path — go through "
                    "the CRC-verified readers (Checkpointer.read_snapshot, "
                    "snapshot_format.verify_snapshot_file + "
                    "map_snapshot_arrays, or fps_tpu.serve)")
        # FPS009: a hand-spelled tenant-namespace literal in a path call
        # is one typo from writing into a neighbor's blast radius.
        if (not self.is_tenant_helper
                and _call_name(node) in _TENANT_PATH_CALLS
                and self._tenant_flavored(node)):
            self._add(
                "FPS009", node,
                f"{_call_name(node)}() spells the tenant namespace by "
                "hand — derive checkpoint/obs/sidecar paths from "
                "fps_tpu.tenancy.TenantPaths (stdlib-only tools: a "
                "mirrored TENANTS_DIRNAME constant)")
        # FPS008: raw sockets outside the wire layer dodge deadlines,
        # bounded retry, and the idempotent reconnect contract.
        if (not self.is_wire_module
                and _call_name(node) in _RAW_SOCKET_CALLS):
            self._add(
                "FPS008", node,
                f"{_call_name(node)}() outside fps_tpu/serve/ — speak "
                "the framed wire through fps_tpu.serve.wire.WireClient "
                "(per-request deadlines, classified bounded retry, "
                "request-id dedupe on reconnect)")
        self.generic_visit(node)

    # -- FPS002 -----------------------------------------------------------

    def _tracer_predicate(self, test):
        """The jnp predicate call inside a bool-context test, if any."""
        stack = [test]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.BoolOp):
                stack.extend(n.values)
            elif isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
                stack.append(n.operand)
            elif isinstance(n, ast.Call):
                name = _call_name(n)
                if name.startswith("jnp.") and (
                        name.split(".", 1)[1] in _TRACER_PREDICATES):
                    return name
        return None

    def _check_bool_context(self, node):
        name = self._tracer_predicate(node.test)
        if name:
            self._add("FPS002", node,
                      f"branch on {name}(...) — use lax.cond/jnp.where in "
                      "traced code, np.* on host values")

    def visit_If(self, node):
        self._check_bool_context(node)
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._check_bool_context(node)
        self.generic_visit(node)

    # -- FPS001 + loops ---------------------------------------------------

    def visit_While(self, node):
        self._check_bool_context(node)
        self._visit_loop(node)

    def visit_For(self, node):
        self._visit_loop(node)

    visit_AsyncFor = visit_For

    def _visit_loop(self, node):
        self._loops.append((node, _loop_target_names(node)))
        self.generic_visit(node)
        self._loops.pop()

    def _check_closure(self, node):
        """FPS001 on a def/lambda lexically inside >=1 loop."""
        if not self._loops:
            return
        bound = _bound_names(node)
        free: set[str] = set()
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    free.add(n.id)
        free -= bound
        for _loop, targets in self._loops:
            captured = sorted(free & targets)
            if captured:
                self._add("FPS001", node,
                          f"closure captures loop variable(s) "
                          f"{', '.join(captured)} by reference — bind as "
                          "a default argument (late-binding traces every "
                          "program against the last iteration's value)")
                return

    # -- FPS003 + function scopes ----------------------------------------

    def _subtree_is_builder(self, node) -> bool:
        for n in ast.walk(node):
            name = _call_name(n)
            if name and name.split(".")[-1] in _TRACE_TRIGGERS:
                return True
        return False

    def visit_FunctionDef(self, node):
        self._check_closure(node)
        entered = False
        if self._trace_depth == 0 and self._subtree_is_builder(node):
            self._trace_depth += 1
            entered = True
        elif self._trace_depth:
            self._trace_depth += 1
            entered = True
        # FPS010 seam: materialize()/__array__ ARE the sanctioned
        # densification path — their bodies may copy.
        allow = node.name in _FPS010_ALLOW_FUNCS
        if allow:
            self._fps010_allow += 1
        # FPS011 seam: writer-loop / background-retrier defs run on
        # their own threads — blocking there is the point.
        allow11 = node.name in _FPS011_ALLOW_FUNCS
        if allow11:
            self._fps011_allow += 1
        self.generic_visit(node)
        if allow11:
            self._fps011_allow -= 1
        if allow:
            self._fps010_allow -= 1
        if entered:
            self._trace_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._check_closure(node)
        self.generic_visit(node)

    def _check_items_iter(self, iter_node, where):
        if self._trace_depth == 0:
            return
        # A sorted()/reversed() wrapper never reaches here: the iter
        # node is then a Name call, not the .items() Attribute call
        # _items_call matches.
        if _items_call(iter_node):
            attr = iter_node.func.attr
            self._add("FPS003", where,
                      f"unsorted .{attr}() iteration inside a compiled-fn "
                      "builder — tree construction must not depend on "
                      "dict insertion history; iterate "
                      f"sorted(....{attr}())")

    def visit_comprehension(self, node):
        self._check_items_iter(node.iter, node.iter)
        self.generic_visit(node)

    def _check_for_iter(self, node):
        self._check_items_iter(node.iter, node)

    # -- FPS004 -----------------------------------------------------------

    def visit_ClassDef(self, node):
        starts_thread = None
        has_sync = False
        for n in ast.walk(node):
            name = _call_name(n)
            if not name:
                continue
            leaf = name.split(".")[-1]
            root = name.split(".")[0]
            if leaf in _THREAD_STARTERS and root in ("threading", leaf):
                starts_thread = starts_thread or n
            if leaf in _SYNC_PRIMITIVES and root in ("threading", "queue",
                                                     leaf):
                has_sync = True
        if starts_thread is not None and not has_sync:
            doc = (ast.get_docstring(node) or "").lower()
            if "thread-safety" not in doc and "thread safety" not in doc:
                self._add(
                    "FPS004", starts_thread,
                    f"class {node.name} starts a thread but declares no "
                    "synchronization primitive (Lock/Condition/Event/"
                    "Queue) and no 'thread-safety:' docstring note")
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>") -> list[LintFinding]:
    """Lint one Python source string; returns findings (empty = clean)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [LintFinding("FPS000", path, e.lineno or 1,
                            f"syntax error: {e.msg}")]
    linter = _Linter(path, source.splitlines())
    # ast.NodeVisitor has no hook ordering for For.iter vs For body with
    # the trace-depth state; run the main visit, then a focused second
    # walk for for-loop iterables (comprehensions are handled inline).
    linter.visit(tree)
    _walk_for_iters(tree, linter)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.rule))


def _walk_for_iters(tree, linter: _Linter) -> None:
    """Second pass for FPS003 on ``for`` statements: re-derive the
    trace-depth context per loop (statement position, not visit order)."""

    def walk(node, depth):
        for child in ast.iter_child_nodes(node):
            d = depth
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if d or linter._subtree_is_builder(child):
                    d += 1
            if isinstance(child, (ast.For, ast.AsyncFor)) and d:
                linter._trace_depth = d
                linter._check_for_iter(child)
                linter._trace_depth = 0
            walk(child, d)

    walk(tree, 0)


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths, select=None) -> list[LintFinding]:
    """Lint every ``.py`` under ``paths``; ``select`` filters rule ids."""
    findings: list[LintFinding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        for finding in lint_source(src, path):
            if select is None or finding.rule in select:
                findings.append(finding)
    return findings
