"""StableHLO program model — the parse layer under the pass suite.

Every correctness claim this framework makes about its data plane is a
claim about the *lowered program*: "the two-tier route has 2 cross-shard
collectives per chunk", "prefetch on/off lowers the identical HLO",
"tables are donated, not copied". Until now those claims were checked by
one-off regexes buried in ``bench.py`` and ad-hoc test asserts. This
module gives them a shared substrate: :class:`HloProgram` parses the
``jax.jit(...).lower(...).as_text()`` StableHLO module into a flat op
list (with payload bytes, replica groups, custom-call targets) plus the
``@main`` argument/result metadata (donation markers, ``jax.result_info``
names) that the analysis passes (:mod:`fps_tpu.analysis.passes`) audit.

Parsing is line-based, matching the textual form jax 0.4.x emits — the
same approach (and the exact same payload/threshold semantics) as the
``count_collectives`` helper this module absorbs from ``bench.py``. It
is deliberately tolerant: unknown ops are still modeled (kind + types),
so a jax upgrade degrades to weaker analysis, never a crash.

Pure text analysis: this module never imports jax. Note that importing
it *through the package* (``import fps_tpu.analysis``) still pulls
``fps_tpu/__init__``, which does — on a jax-free login node use
``tools/audit_programs.py --hlo DUMP.txt``, which loads the analysis
package via a stub root instead.
"""

from __future__ import annotations

import dataclasses
import json
import re

# Cross-shard data-plane collectives (the set bench.py's tiered A/B counts).
COLLECTIVE_KINDS = (
    "all_gather",
    "all_reduce",
    "all_to_all",
    "reduce_scatter",
    "collective_permute",
)

# Infrastructure custom_calls jax/XLA emit for sharding annotation and
# shard_map manual-mode boundaries — pure metadata, no host transfer.
INFRA_CUSTOM_CALLS = frozenset({
    "Sharding",
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
    "annotate_device_placement",
})

_OP_RE = re.compile(r'^\s*%\S+\s*=\s*"?stablehlo\.([a-z_0-9]+)"?')
_TENSOR_RE = re.compile(r"tensor<([0-9]+(?:x[0-9]+)*)x([a-z]+[0-9]+)>")
_GROUPS_RE = re.compile(
    r"replica_groups\s*=\s*dense<(.*?)>\s*:\s*tensor<([0-9]+)x([0-9]+)xi64>"
)
_TARGET_RE = re.compile(r"custom_call\s+@([A-Za-z0-9_.]+)")
# Attribute dicts on @main args/results may hold quoted strings that
# themselves contain braces (mhlo.sharding = "{devices=[8,1]<=[8]}") —
# a naive [^}]* stops inside the quote and drops every attribute sorted
# after it (tf.aliasing_output sorts after mhlo.sharding). Allow quoted
# runs and one level of brace nesting.
_ATTRS = r'\{(?:[^{}"]|"[^"]*"|\{[^{}]*\})*\}'
_ARG_RE = re.compile(
    r"%arg(\d+):\s*(tensor<[^>]*>|![^,\s){]+)\s*(" + _ATTRS + r")?"
)
_RESULT_INFO_RE = re.compile(r'jax\.result_info\s*=\s*"([^"]*)"')
# Float element types inside tensor<...> forms: the dims and dtype are
# one word-char run ("64x8xf32"), so anchor on the preceding 'x' or '<'
# instead of a word boundary.
_FLOAT_RE = re.compile(r"[x<](bf16|f16|f32|f64)\b")

# How far past a region-opening op line the closing `})` carrying the
# operand/result signature may sit (all_reduce bodies are 3-4 lines).
_REGION_LOOKAHEAD = 12


def tensor_bytes(type_str: str) -> int:
    """Largest tensor payload (numel * itemsize) named in ``type_str``.

    Same semantics as the original ``bench.count_collectives`` helper:
    scalars (``tensor<f32>``) don't match, sub-byte dtypes (i1) floor to
    0 — the accounting tracks bulk data-plane traffic, not flags."""
    best = 0
    for dims, dt in _TENSOR_RE.findall(type_str):
        size = 1
        for d in dims.split("x"):
            size *= int(d)
        best = max(best, size * (int(re.sub(r"[a-z]+", "", dt)) // 8))
    return best


def float_widths(type_str: str) -> list[int]:
    """Bit widths of every float element type named in ``type_str``
    (``bf16`` reports 16)."""
    out = []
    for m in _FLOAT_RE.finditer(type_str):
        tok = m.group(1)
        out.append(16 if tok == "bf16" else int(tok[1:]))
    return out


def _parse_groups(content: str, n: int, m: int):
    """``dense<...>`` replica-groups payload → tuple of id tuples.

    Bracketed form is JSON-compatible after whitespace normalization; the
    splat form (``dense<0> : tensor<1x1xi64>``) only occurs for the
    trivial single-group case."""
    content = content.strip()
    if content.startswith("["):
        try:
            groups = json.loads(content)
            return tuple(tuple(int(i) for i in g) for g in groups)
        except (ValueError, TypeError):
            return None
    try:
        v = int(content)
    except ValueError:
        return None
    if n == 1 and m == 1:
        return ((v,),)
    return None  # splat over a non-trivial shape: shape info only


@dataclasses.dataclass(frozen=True)
class Collective:
    """One cross-shard collective, as the structured profile reports it:
    ``(kind, payload_bytes, replica_groups)`` plus the group size used
    for the singleton-mesh-axis exclusion."""

    kind: str
    payload_bytes: int
    replica_groups: tuple[tuple[int, ...], ...] | None
    group_size: int | None = None

    def as_tuple(self):
        return (self.kind, self.payload_bytes, self.replica_groups)


@dataclasses.dataclass(frozen=True)
class HloOp:
    """One ``stablehlo.*`` op line (region signatures folded in)."""

    kind: str
    line: int  # 1-indexed line number of the op in the module text
    text: str
    payload_bytes: int
    replica_groups: tuple[tuple[int, ...], ...] | None = None
    group_size: int | None = None
    custom_target: str | None = None

    @property
    def is_collective(self) -> bool:
        return self.kind in COLLECTIVE_KINDS


@dataclasses.dataclass(frozen=True)
class HloArg:
    """One ``@main`` argument: type plus whether jax marked its buffer
    as donated (``jax.buffer_donor``) / aliased to an output
    (``tf.aliasing_output``)."""

    index: int
    type: str
    donated: bool
    attrs: str = ""


@dataclasses.dataclass(frozen=True)
class HloResult:
    """One ``@main`` result: type plus the ``jax.result_info`` path
    (e.g. ``[0]['weights']`` — element 0 of the return tuple, dict key
    'weights')."""

    index: int
    type: str
    info: str = ""


class HloProgram:
    """Parsed model of one lowered StableHLO module."""

    def __init__(self, text: str, ops, args, results):
        self.text = text
        self.ops: list[HloOp] = list(ops)
        self.args: list[HloArg] = list(args)
        self.results: list[HloResult] = list(results)

    # -- construction -----------------------------------------------------

    @classmethod
    def from_text(cls, text: str) -> "HloProgram":
        lines = text.splitlines()
        ops: list[HloOp] = []
        for i, line in enumerate(lines):
            m = _OP_RE.match(line)
            if not m:
                continue
            kind = m.group(1)
            payload = tensor_bytes(line)
            region_sig = ""
            if "({" in line:
                # Region-carrying op (all_reduce/reduce_scatter/reduce):
                # the operand/result types sit on the region's CLOSING
                # line, not the op line (whose only tensor<> is the
                # replica-groups constant).
                for j in range(i + 1, min(i + _REGION_LOOKAHEAD, len(lines))):
                    if "})" in lines[j]:
                        region_sig = lines[j]
                        payload = max(payload, tensor_bytes(region_sig))
                        break
            groups = group_size = None
            g = _GROUPS_RE.search(line)
            if g:
                n, msize = int(g.group(2)), int(g.group(3))
                group_size = msize
                groups = _parse_groups(g.group(1), n, msize)
            target = None
            if kind == "custom_call":
                t = _TARGET_RE.search(line)
                target = t.group(1) if t else None
            ops.append(HloOp(
                kind=kind, line=i + 1, text=line.strip(),
                payload_bytes=payload, replica_groups=groups,
                group_size=group_size, custom_target=target,
            ))
        args, results = cls._parse_main(text)
        return cls(text, ops, args, results)

    @staticmethod
    def _parse_main(text: str) -> tuple[list[HloArg], list[HloResult]]:
        m = re.search(r"func\.func public @main\((.*)$", text, re.MULTILINE)
        if not m:
            return [], []
        sig = m.group(1)
        # The signature is one (long) line: "...args...) -> (results) {".
        if "->" in sig:
            args_part, res_part = sig.split("->", 1)
        else:
            args_part, res_part = sig, ""
        args = []
        for am in _ARG_RE.finditer(args_part):
            attrs = am.group(3) or ""
            args.append(HloArg(
                index=int(am.group(1)),
                type=am.group(2),
                donated=("jax.buffer_donor" in attrs
                         or "tf.aliasing_output" in attrs),
                attrs=attrs,
            ))
        results = []
        # Results: "(tensor<...> {jax.result_info = "..."}, ...) {"
        # Walk tensor types in order, pairing each with the result_info
        # attribute block that immediately follows it (if any).
        for idx, tm in enumerate(re.finditer(
                r"(tensor<[^>]*>|![^,\s){]+)(\s*(?:" + _ATTRS + r"))?",
                res_part)):
            attrs = tm.group(2) or ""
            im = _RESULT_INFO_RE.search(attrs)
            info = im.group(1) if im else ""
            results.append(HloResult(index=idx, type=tm.group(1), info=info))
        return args, results

    # -- queries ----------------------------------------------------------

    def by_kind(self, kind: str) -> list[HloOp]:
        return [op for op in self.ops if op.kind == kind]

    def custom_calls(self) -> list[HloOp]:
        return [op for op in self.ops if op.kind == "custom_call"]

    def collectives(self, min_bytes: int = 1024) -> list[HloOp]:
        """Cross-shard collectives whose payload is at least ``min_bytes``.

        Excluded: singleton replica groups (a size-1 mesh axis — no
        communication at all) and sub-threshold payloads (the per-step
        scalar metric psums), so the list tracks data-plane table/batch
        traffic. Static per compiled program: an op inside the step scan
        counts once, which is exactly the per-chunk program the two-tier
        A/B's claim is about."""
        out = []
        for op in self.ops:
            if not op.is_collective:
                continue
            if op.group_size is not None and op.group_size <= 1:
                continue
            if op.payload_bytes >= min_bytes:
                out.append(op)
        return out

    def profile(self, min_bytes: int = 1024) -> list[Collective]:
        """Structured collective profile: ``[(kind, payload_bytes,
        replica_groups)]`` per qualifying collective (see
        :meth:`collectives`)."""
        return [
            Collective(op.kind, op.payload_bytes, op.replica_groups,
                       op.group_size)
            for op in self.collectives(min_bytes)
        ]


def collective_profile(text: str, min_bytes: int = 1024) -> list[Collective]:
    """Structured cross-shard collective accounting of a lowered
    (StableHLO) program: one ``Collective(kind, payload_bytes,
    replica_groups)`` per qualifying op (payload >= ``min_bytes``,
    singleton replica groups excluded). The structured successor of
    ``bench.count_collectives`` — ``len()`` of this list is that count."""
    return HloProgram.from_text(text).profile(min_bytes)


def count_collectives(text: str, min_bytes: int = 1024) -> int:
    """Cross-shard collectives in a lowered (StableHLO) program whose
    payload is at least ``min_bytes`` (see :func:`collective_profile` for
    the structured form; this is the historical ``bench.py`` API)."""
    return len(collective_profile(text, min_bytes))
