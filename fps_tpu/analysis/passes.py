"""The analysis passes: each audits one plane of the program contract.

Every pass is a pure function of ``(HloProgram, ProgramContract) ->
[Violation]`` — composable, orderless, and individually proven
non-vacuous by a seeded-mutation test (``tests/test_analysis.py``
deliberately breaks each contract in a toy program and asserts the
corresponding pass — and only it — reports the break).

* :class:`CollectiveBudget` — count and byte-payload caps per collective
  kind (the static form of the tiered A/B's 5→2 claim).
* :class:`HostTransferDetector` — no unexpected custom_call / infeed /
  outfeed / send / recv inside the step (a stray ``io_callback`` or
  debug print in the hot loop is a per-step host round trip).
* :class:`DonationAudit` — canonical tables donated/aliased in-place
  (an un-donated table doubles HBM and pays a copy per dispatch).
* :class:`DtypeDriftDetector` — no accidental float widening between
  pull → compute → push (an f64 op, or a widening convert, silently
  doubles bandwidth on the whole downstream dataflow).
* :class:`ReplicaConsistency` — tiered programs actually contain the
  shard-axis reconcile psum at the hot head's payload size (the static
  form of PR 5's reconcile invariant: hot updates are dominated by one
  psum, not re-routed through gathered scatters).
"""

from __future__ import annotations

from fps_tpu.analysis.contract import ProgramContract, Violation
from fps_tpu.analysis.hlo import (
    INFRA_CUSTOM_CALLS,
    HloProgram,
    float_widths,
)

__all__ = [
    "AnalysisPass",
    "CollectiveBudget",
    "HostTransferDetector",
    "DonationAudit",
    "DtypeDriftDetector",
    "ReplicaConsistency",
    "DEFAULT_PASSES",
]


class AnalysisPass:
    """Base shape: stateless, named, returns violations (empty = clean)."""

    name = "analysis"

    def run(self, program: HloProgram,
            contract: ProgramContract) -> list[Violation]:
        raise NotImplementedError

    def _v(self, summary: str, op=None) -> Violation:
        return Violation(
            pass_name=self.name, summary=summary,
            op_kind=getattr(op, "kind", ""), line=getattr(op, "line", 0),
        )


class CollectiveBudget(AnalysisPass):
    """Total / per-kind collective count and payload-byte budgets."""

    name = "collective_budget"

    def run(self, program, contract):
        colls = program.collectives(contract.min_collective_payload)
        exact = contract.exact_collectives
        out = []
        n = len(colls)
        if contract.max_collectives is not None and (
                n != contract.max_collectives if exact
                else n > contract.max_collectives):
            verb = ("differ from the pinned budget" if exact and
                    n < contract.max_collectives else "exceed the budget")
            out.append(self._v(
                f"{n} cross-shard collectives {verb} "
                f"of {contract.max_collectives} (>= "
                f"{contract.min_collective_payload}B payload each)"
            ))
        total = sum(op.payload_bytes for op in colls)
        if (contract.max_collective_bytes is not None
                and total > contract.max_collective_bytes):
            out.append(self._v(
                f"{total} collective payload bytes exceed the budget of "
                f"{contract.max_collective_bytes}"
            ))
        if contract.per_kind_max:
            counts: dict[str, int] = {}
            for op in colls:
                counts[op.kind] = counts.get(op.kind, 0) + 1
            for kind, cap in sorted(contract.per_kind_max.items()):
                have = counts.get(kind, 0)
                if have > cap:
                    out.append(self._v(
                        f"{have} {kind} ops exceed the per-kind "
                        f"budget of {cap}"
                    ))
                elif exact and have < cap:
                    out.append(self._v(
                        f"{have} {kind} ops fall short of the pinned "
                        f"per-kind budget of {cap}"
                    ))
            if exact:
                for kind in sorted(set(counts) - set(contract.per_kind_max)):
                    out.append(self._v(
                        f"{counts[kind]} {kind} ops but the kind is not "
                        f"in the pinned per-kind budget"
                    ))
        return out


class HostTransferDetector(AnalysisPass):
    """No host transfers inside the step program.

    Flags infeed/outfeed/send/recv outright and any ``custom_call``
    whose target is neither shard_map/sharding infrastructure
    (:data:`~fps_tpu.analysis.hlo.INFRA_CUSTOM_CALLS`) nor explicitly
    allowed by the contract — the lowering of ``io_callback`` /
    ``jax.debug.*`` / ``pure_callback`` is a custom_call into the host
    Python runtime, a per-step synchronization the step budget never
    priced in."""

    name = "host_transfer"

    _HARD_KINDS = ("infeed", "outfeed", "send", "recv")

    def run(self, program, contract):
        out = []
        allowed = INFRA_CUSTOM_CALLS | set(contract.allow_host_transfers)
        for op in program.ops:
            if op.kind in self._HARD_KINDS:
                out.append(self._v(
                    f"host transfer op stablehlo.{op.kind} inside the "
                    f"compiled step (line {op.line})", op))
            elif op.kind == "custom_call":
                target = op.custom_target or "?"
                if target not in allowed:
                    out.append(self._v(
                        f"unexpected custom_call @{target} (line "
                        f"{op.line}) — host callback / opaque transfer "
                        "not declared in the contract", op))
        return out


class DonationAudit(AnalysisPass):
    """Canonical tables donated/aliased in-place, no silent copies.

    Table outputs are identified by their ``jax.result_info`` path —
    the drivers return ``(tables, local_state, metrics)``, so every
    ``[0][...]`` result is a table leaf. For each, a distinct input
    argument of the identical tensor type must carry a donation marker
    (``jax.buffer_donor`` / ``tf.aliasing_output``); otherwise XLA
    double-buffers the table and every dispatch pays a copy."""

    name = "donation"

    def run(self, program, contract):
        if not contract.donated_tables:
            return []
        if not program.results or not program.args:
            return []  # no @main metadata — nothing to audit
        donated_pool: dict[str, int] = {}
        for a in program.args:
            if a.donated:
                donated_pool[a.type] = donated_pool.get(a.type, 0) + 1
        out = []
        for r in program.results:
            if not r.info.startswith("[0]"):
                continue
            if donated_pool.get(r.type, 0) > 0:
                donated_pool[r.type] -= 1
            else:
                label = r.info[3:] or f"result {r.index}"
                out.append(self._v(
                    f"table output {label} ({r.type}) has no donated "
                    "input buffer of matching type — the update is a "
                    "copy, not in-place"
                ))
        return out


class DtypeDriftDetector(AnalysisPass):
    """No accidental float widening in the step's dataflow.

    Two tiers: any float wider than ``contract.max_float_bits``
    anywhere in the program (an f64 creeping in via a Python float or a
    host-side default doubles bandwidth downstream), and — unless
    allowed — float→wider-float ``stablehlo.convert`` ops (a bf16 table
    pulled and silently computed in f32 defeats the narrow-dtype
    choice the table spec made)."""

    name = "dtype_drift"

    def run(self, program, contract):
        out = []
        wide_lines = []
        for op in program.ops:
            widths = float_widths(op.text)
            if widths and max(widths) > contract.max_float_bits:
                wide_lines.append(op)
        if wide_lines:
            op = wide_lines[0]
            out.append(self._v(
                f"{len(wide_lines)} op(s) touch floats wider than "
                f"f{contract.max_float_bits} (first: stablehlo.{op.kind} "
                f"at line {op.line})", op))
        if not contract.allow_widening_converts:
            for op in program.by_kind("convert"):
                widths = float_widths(op.text)
                # A widening float->float convert names two widths with
                # the result strictly wider (operand type precedes the
                # result type in "(tensor<..A>) -> tensor<..B>").
                if len(widths) >= 2 and widths[-1] > widths[0]:
                    out.append(self._v(
                        f"widening convert f{widths[0]}->f{widths[-1]} at "
                        f"line {op.line} — dtype drift between pull/"
                        "compute/push", op))
        return out


class ReplicaConsistency(AnalysisPass):
    """Tiered programs must reconcile through the shard axis.

    The two-tier storage's correctness story (PR 5, sharded in PR 10 per
    arXiv:2004.13336) is that hot-tier replica updates are *dominated by
    one window-end collective exchange*: per-device pending deltas fold
    into replica + canonical head through a **reduce-scatter** over the
    shard axis (each replica applies its disjoint 1/S slice, re-broadcast
    by the paired all-gather), or — for the extremum combines, and in
    pre-PR-10 programs — a full-head ``all_reduce``. A program that
    claims tiering but lowers with neither either silently dropped the
    reconcile (divergent replicas) or re-routed hot traffic through the
    gathered scatters (the budget the tier exists to avoid).

    Heuristic scope note: the op is identified by kind + shard group +
    payload size (>= the replicated head's bytes), not by dataflow — a
    cold-route reduce-scatter of at least that size also satisfies it.
    The collective BUDGET pass pins the exact op census; this pass only
    asserts the reconcile-shaped exchange exists."""

    name = "replica_consistency"

    _KINDS = ("reduce_scatter", "all_reduce")

    def run(self, program, contract):
        if not contract.require_shard_psum:
            return []
        want = contract.hot_reconcile_bytes
        for kind in self._KINDS:
            for op in program.by_kind(kind):
                if op.group_size is not None and op.group_size <= 1:
                    continue
                if (contract.shard_group_size is not None
                        and op.group_size is not None
                        and op.group_size != contract.shard_group_size):
                    continue
                if op.payload_bytes >= want:
                    return []
        side = (f" over groups of {contract.shard_group_size}"
                if contract.shard_group_size else "")
        return [self._v(
            f"no hot-tier reconcile exchange found: expected a "
            f"reduce_scatter (or extremum/legacy all_reduce){side} with "
            f"payload >= {want}B — replica and canonical table cannot "
            "stay consistent without it"
        )]


DEFAULT_PASSES = (
    CollectiveBudget(),
    HostTransferDetector(),
    DonationAudit(),
    DtypeDriftDetector(),
    ReplicaConsistency(),
)
