"""Program contracts and certificates — what a compiled step program
must look like, and the machine-readable proof that it does.

A :class:`ProgramContract` declares the budgets and invariants one
``(workload, route, tiering)`` configuration promises about its lowered
step program: how many cross-shard collectives (and how many payload
bytes) it may move per chunk, that its canonical tables are donated,
that no host transfer hides inside the step, that no dtype drift widens
the compute plane, and — for tiered programs — that the hot-tier
reconcile psum is actually present. :func:`certify` runs the pass suite
(:mod:`fps_tpu.analysis.passes`) over a lowered program against a
contract and returns a :class:`Certificate` whose ``to_json()`` form is
what ``tools/audit_programs.py`` writes and chaos_sweep attaches to its
digest.

:class:`ProgramAuditor` is the live form: ``Trainer(audit=...)`` calls
it at compile time for every program it builds, recording
``analysis.certified_programs`` / ``analysis.contract_violations``
metrics and an ``analysis.contract_violation`` event per finding through
``fps_tpu.obs`` (strict mode raises instead — CI semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from fps_tpu.analysis.hlo import HloProgram

__all__ = [
    "ProgramContract",
    "Violation",
    "Certificate",
    "ContractViolationError",
    "ProgramAuditor",
    "as_auditor",
    "certify",
    "contract_for_trainer",
]


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    """Static-shape budget for one compiled step program.

    ``None`` / falsy fields mean "not asserted" — a default contract
    checks the structural invariants (host transfers, donation, dtype
    drift) without pinning collective counts, so it is safe to apply to
    any workload; the audit tool pins explicit budgets per workload.
    """

    name: str = "default"
    # -- CollectiveBudget -------------------------------------------------
    #: Max qualifying cross-shard collectives in the program (None = any).
    max_collectives: int | None = None
    #: Max total payload bytes across qualifying collectives.
    max_collective_bytes: int | None = None
    #: Per-kind count caps, e.g. {"all_reduce": 1} (unlisted kinds free).
    per_kind_max: Mapping[str, int] | None = None
    #: Treat the count budgets as PINNED exact values instead of
    #: ceilings: a removed collective (or an unlisted kind appearing)
    #: fails too — the audit tool's re-pinning workflow, where any
    #: structural change to the program must show up as a budget diff.
    exact_collectives: bool = False
    #: Payload threshold below which a collective is control-plane noise
    #: (scalar metric psums) — same default as the tiered A/B accounting.
    min_collective_payload: int = 1024
    # -- HostTransferDetector ---------------------------------------------
    #: Extra custom_call targets to allow beyond the sharding/shard_map
    #: infrastructure set (e.g. a deliberate io_callback tap).
    allow_host_transfers: tuple[str, ...] = ()
    # -- DonationAudit ----------------------------------------------------
    #: Require every table-typed output to have a donated input buffer.
    donated_tables: bool = True
    # -- DtypeDriftDetector -----------------------------------------------
    #: Widest float allowed anywhere in the program (f64 ops = drift).
    max_float_bits: int = 32
    #: Allow float->wider-float stablehlo.convert ops (off: a bf16 input
    #: silently widened to f32 inside the step is flagged).
    allow_widening_converts: bool = False
    # -- ReplicaConsistency -----------------------------------------------
    #: Tiered programs must contain the hot-tier reconcile psum
    #: (all_reduce, group_size > 1) ...
    require_shard_psum: bool = False
    #: ... whose payload is at least this many bytes (H*dim*itemsize of
    #: the smallest tiered table; 0 = any size).
    hot_reconcile_bytes: int = 0
    #: Expected reconcile group size (num_shards); None = any > 1.
    shard_group_size: int | None = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("per_kind_max") is not None:
            d["per_kind_max"] = dict(d["per_kind_max"])
        d["allow_host_transfers"] = list(d["allow_host_transfers"])
        return d


@dataclasses.dataclass(frozen=True)
class Violation:
    """One contract violation, attributed to the pass that found it."""

    pass_name: str
    summary: str
    op_kind: str = ""
    line: int = 0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Certificate:
    """The audit result for one program: the measured collective budget
    plus every violation (empty = certified clean)."""

    program: str
    contract: ProgramContract
    collectives: list  # [Collective]
    violations: list  # [Violation]

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def collective_count(self) -> int:
        return len(self.collectives)

    @property
    def collective_bytes(self) -> int:
        return sum(c.payload_bytes for c in self.collectives)

    def per_kind(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for c in self.collectives:
            k = out.setdefault(c.kind, {"count": 0, "bytes": 0})
            k["count"] += 1
            k["bytes"] += c.payload_bytes
        return dict(sorted(out.items()))

    def to_json(self) -> dict:
        return {
            "program": self.program,
            "ok": self.ok,
            "contract": self.contract.to_json(),
            "collectives": {
                "count": self.collective_count,
                "bytes": self.collective_bytes,
                "per_kind": self.per_kind(),
                "ops": [
                    {"kind": c.kind, "payload_bytes": c.payload_bytes,
                     "replica_groups": (
                         [list(g) for g in c.replica_groups]
                         if c.replica_groups is not None else None)}
                    for c in self.collectives
                ],
            },
            "violations": [v.to_json() for v in self.violations],
        }


class ContractViolationError(AssertionError):
    """A strict audit found contract violations (carries the
    certificate on ``.certificate``)."""

    def __init__(self, certificate: Certificate):
        self.certificate = certificate
        lines = [f"program {certificate.program!r} violates contract "
                 f"{certificate.contract.name!r}:"]
        lines += [f"  [{v.pass_name}] {v.summary}"
                  for v in certificate.violations]
        super().__init__("\n".join(lines))


def certify(text, contract: ProgramContract | None = None, *,
            program: str = "program", passes=None) -> Certificate:
    """Run the pass suite over one lowered program and return the
    certificate. ``text`` is ``.lower(...).as_text()`` output (or an
    already-parsed :class:`HloProgram`)."""
    from fps_tpu.analysis.passes import DEFAULT_PASSES

    contract = contract or ProgramContract()
    prog = (text if isinstance(text, HloProgram)
            else HloProgram.from_text(text))
    violations: list[Violation] = []
    for p in (passes if passes is not None else DEFAULT_PASSES):
        violations.extend(p.run(prog, contract))
    return Certificate(
        program=program,
        contract=contract,
        collectives=prog.profile(contract.min_collective_payload),
        violations=violations,
    )


def contract_for_trainer(trainer, mode: str = "sync") -> ProgramContract:
    """Structural default contract derived from a Trainer's own static
    resolution: donation from ``config.donate``, float width from the
    widest table dtype, and — when the two-tier storage resolves ON —
    the reconcile-psum requirement sized to the smallest tiered head.

    Collective COUNTS are deliberately not pinned here (they are
    workload-shaped); pass an explicit :class:`ProgramContract` — like
    ``tools/audit_programs.py`` does — to pin them.
    """
    import numpy as np

    bits = 32
    for spec in trainer.store.specs.values():
        bits = max(bits, np.dtype(spec.dtype).itemsize * 8)
    tier = trainer._hot_tier_map()
    hot_bytes = 0
    if tier:
        hot_bytes = min(
            H * trainer.store.specs[name].dim
            * np.dtype(trainer.store.specs[name].dtype).itemsize
            for name, H in tier.items()
        )
    return ProgramContract(
        name=f"trainer/{mode}" + ("/tiered" if tier else ""),
        donated_tables=bool(trainer.config.donate),
        max_float_bits=bits,
        require_shard_psum=bool(tier),
        hot_reconcile_bytes=hot_bytes,
        shard_group_size=trainer.num_shards if tier else None,
    )


class ProgramAuditor:
    """Certifies lowered programs and reports through ``fps_tpu.obs``.

    ``contract=None`` lets the caller supply one per certify() call
    (the Trainer hook derives :func:`contract_for_trainer` then);
    ``strict=True`` raises :class:`ContractViolationError` on any
    violation — compile-time CI semantics — instead of only recording.
    Certificates accumulate on ``self.certificates`` for end-of-run
    reporting.
    """

    def __init__(self, contract: ProgramContract | None = None, *,
                 recorder=None, strict: bool = False, passes=None):
        self.contract = contract
        self.recorder = recorder
        self.strict = strict
        self.passes = passes
        self.certificates: list[Certificate] = []

    def certify(self, program: str, text, *,
                contract: ProgramContract | None = None,
                recorder=None) -> Certificate:
        contract = contract or self.contract or ProgramContract()
        cert = certify(text, contract, program=program, passes=self.passes)
        self.certificates.append(cert)
        self._report(cert, recorder if recorder is not None
                     else self.recorder)
        if self.strict and not cert.ok:
            raise ContractViolationError(cert)
        return cert

    def _report(self, cert: Certificate, rec) -> None:
        from fps_tpu.obs import events

        def _inc(name, value=1.0, **labels):
            if rec is not None:
                rec.inc(name, value, **labels)
            else:
                events.record_metric("inc", name, value, **labels)

        def _event(etype, **fields):
            if rec is not None:
                rec.event(etype, **fields)
            else:
                events.emit(etype, **fields)

        if cert.ok:
            _inc("analysis.certified_programs")
            return
        for v in cert.violations:
            _inc("analysis.contract_violations", rule=v.pass_name)
            _event("analysis.contract_violation", program=cert.program,
                   contract=cert.contract.name, rule=v.pass_name,
                   summary=v.summary)


def as_auditor(audit) -> ProgramAuditor | None:
    """Normalize the Trainer's ``audit=`` value: an auditor passes
    through; a :class:`ProgramContract` wraps; ``True`` builds a default
    recording auditor and ``"strict"`` a raising one. ``None`` and
    ``False`` mean disabled (returns None) — so a boolean flag can be
    wired straight through."""
    if audit is None or audit is False:
        return None
    if isinstance(audit, ProgramAuditor):
        return audit
    if isinstance(audit, ProgramContract):
        return ProgramAuditor(contract=audit)
    if audit is True:
        return ProgramAuditor()
    if audit == "strict":
        return ProgramAuditor(strict=True)
    raise TypeError(
        f"audit must be a ProgramAuditor, ProgramContract, True, or "
        f"'strict' — got {audit!r}"
    )
