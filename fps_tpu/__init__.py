"""fps_tpu — a TPU-native parameter-server framework.

A ground-up rebuild of the capabilities of ``lucaRadicalbit/flink-parameter-server-1``
(Scala on Apache Flink DataStream) as an idiomatic JAX/XLA framework for TPU:

* parameters live in **sharded jax arrays in HBM** (the reference's server shards —
  ``ParameterServerLogic`` instances holding hash partitions of the id space;
  expected upstream path ``src/main/scala/hu/sztaki/ilab/ps/``),
* **pull** is a collective gather (``all_gather`` + ``psum_scatter`` over the ICI
  mesh) instead of a Flink record routed by ``partitionCustom(hash(paramId))``,
* **push** is a collective scatter-add instead of a ``Push(id, delta)`` envelope,
* the training loop is a ``jax.lax.scan`` / ``while_loop`` step driver instead of
  Flink's ``ConnectedIterativeStreams`` feedback edge,
* async/SSP bounded staleness is a snapshot-refresh schedule inside the compiled
  loop instead of the reference's free-running operator asynchrony.

The user contract mirrors the reference's two-trait API (``WorkerLogic`` /
``ParameterServerLogic``) in functional form — see :mod:`fps_tpu.core.api`.
"""

from fps_tpu.utils import compat as _compat

_compat.install()

from fps_tpu.core.api import ServerLogic, WorkerLogic, StepOutput
from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
from fps_tpu.core.resilience import (
    GuardConfig,
    PoisonedStreamError,
    RollbackPolicy,
    SnapshotCorruptionError,
)
from fps_tpu.core.checkpoint import AsyncCheckpointer, Checkpointer
from fps_tpu.core.store import TableSpec, ParamStore
from fps_tpu.parallel.mesh import init_distributed, make_ps_mesh
from fps_tpu import obs
from fps_tpu import serve
from fps_tpu import supervise

__version__ = "0.1.0"

__all__ = [
    "ServerLogic",
    "WorkerLogic",
    "StepOutput",
    "TableSpec",
    "ParamStore",
    "Trainer",
    "TrainerConfig",
    "num_workers_of",
    "DeviceDataset",
    "DeviceEpochPlan",
    "make_ps_mesh",
    "init_distributed",
    "GuardConfig",
    "RollbackPolicy",
    "SnapshotCorruptionError",
    "PoisonedStreamError",
    "Checkpointer",
    "AsyncCheckpointer",
    "obs",
    "serve",
    "supervise",
    "__version__",
]
