"""Multi-tenant blast-radius chaos scenarios (the tentpole's proof).

Every scenario here runs M model instances on one fleet through
:class:`fps_tpu.tenancy.TenantManager` (or, for the in-process serving
leg, two :class:`~fps_tpu.tenancy.paths.TenantPaths` namespaces side by
side), injects a fault into EXACTLY ONE tenant, and then proves the
blast radius held:

* every non-injected tenant finishes **bit-identical to its solo run**
  (the same workload run alone, no neighbors) — isolation measured in
  bytes, not vibes;
* :func:`fps_tpu.tenancy.audit.audit_namespaces` finds ZERO files
  outside the declared tenant namespaces — no plane wrote into a
  neighbor's (or the fleet root's) directory, faulted or not;
* where the injected tenant recovers through supervisor restarts, the
  per-scenario ``time_to_recovered_s`` is extracted from its OWN
  supervisor journal (:func:`fps_tpu.supervise.supervisor.
  recovery_times`) and carried into the sweep digest.

Shared by ``tools/chaos_sweep.py`` (the ``tenant_*`` scenarios) so the
isolation contract is pinned by the same harness as every other failure
mode. The workload is :mod:`fps_tpu.testing.supervised_demo`'s tiny
logreg child — the established deterministic unit of bit-identity.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from fps_tpu.testing import supervised_demo as sd

_ROOT = sd._ROOT

# Tenant names used by every scenario: ``a`` is ALWAYS the injected
# tenant, ``b`` the innocent neighbor whose bit-identity is the verdict.
TENANT_INJECTED = "a"
TENANT_NEIGHBOR = "b"
SCENARIO_TENANT_CRASH_AT = 3
# ENOSPC brownout schedule for tenant a's snapshot plane: occurrences
# 2..9 of (snapshot, write) fail — long enough to exhaust the retry
# budget (4 attempts/publish) at least once, short enough to recover.
SCENARIO_TENANT_ENOSPC_START = 2
SCENARIO_TENANT_ENOSPC_COUNT = 8
# Noisy-neighbor planner profile: a feature table big enough that the
# demo's --hot-tier row counts derived from the plan are meaningful.
SCENARIO_TENANT_NN_NF = 4096
SCENARIO_TENANT_NN_DIM = 4
SCENARIO_TENANT_NN_BUDGET = 48 * 1024


def _env():
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    return env


def _demo_cmd(*extra):
    """The per-tenant child argv template: standard scenario workload
    with the namespace placeholders the TenantManager resolves."""
    return (sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *sd.SCENARIO_DEMO_ARGS,
            "--ckpt-dir", "{ckpt}", "--out", "{out}", "--obs-dir", "{obs}",
            *extra)


def _solo_run(tmpdir: str, tag: str, *extra, timeout: float):
    """The bit-identity reference: the same workload run ALONE, outside
    any tenant namespace. Returns ``(ok, out_path, tail)``."""
    d = os.path.join(tmpdir, f"solo_{tag}")
    out = os.path.join(tmpdir, f"solo_{tag}.npz")
    r = subprocess.run(
        [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
         *sd.SCENARIO_DEMO_ARGS, "--ckpt-dir", d, "--out", out, *extra],
        env=_env(), cwd=_ROOT, capture_output=True, text=True,
        timeout=timeout)
    return r.returncode == 0, out, (r.stdout + r.stderr)[-1000:]


def _manager(root: str, specs):
    from fps_tpu.supervise.supervisor import SupervisorConfig
    from fps_tpu.tenancy import TenantManager

    return TenantManager(
        root, specs,
        config=SupervisorConfig(
            stall_timeout_s=60.0, startup_grace_s=300.0, term_grace_s=2.0,
            backoff_base_s=0.2, max_restarts=2, poll_interval_s=0.2),
        base_env=_env())


def _tenant_out_meta(mgr, name: str) -> dict:
    try:
        with open(mgr.paths[name].out_path + ".meta.json",
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _bit_identical(out_a: str, out_b: str) -> bool:
    import numpy as np

    return bool(os.path.exists(out_a) and os.path.exists(out_b)
                and np.array_equal(np.load(out_a)["weights"],
                                   np.load(out_b)["weights"]))


def _recovery(journal_path: str) -> dict:
    """Per-tenant recovery-time evidence from its OWN supervisor
    journal; ``time_to_recovered_s`` is the slowest recovery (the figure
    the sweep digest surfaces)."""
    from fps_tpu.supervise.supervisor import recovery_times

    times = recovery_times(journal_path)
    return {"count": len(times),
            "times_s": [round(t, 3) for t in times],
            "time_to_recovered_s": (round(max(times), 3)
                                    if times else None)}


def _audit(root: str) -> dict:
    from fps_tpu.tenancy import audit_namespaces

    return audit_namespaces(root, [TENANT_INJECTED, TENANT_NEIGHBOR])


def run_tenant_poison_isolation_scenario(tmpdir: str, *,
                                         timeout: float = 600):
    """Tenant ``a``'s child crashes deterministically at the same chunk
    on every attempt (the poison-batch flap) while tenant ``b`` trains
    the identical workload beside it. The contract:

    * ``a``'s OWN supervisor converges: crash, crash → quarantine the
      chunk, third attempt completes skipping it (2 restarts, the
      quarantined index in ``a``'s digest and out-meta);
    * ``b`` is UNTOUCHED: zero restarts, nothing quarantined, and its
      final weights BIT-IDENTICAL to its solo run — a neighbor's poison
      never costs an innocent tenant a single bit;
    * both fencing epochs stay at their seeded value 1 — ``a``'s
      restarts never order against ``b``'s namespace;
    * the post-run namespace audit finds zero cross-tenant writes;
    * ``a``'s recovery times are measurable from ``a``'s own journal.
    """
    from fps_tpu.tenancy import TenantSpec

    ok, solo_out, tail = _solo_run(tmpdir, TENANT_NEIGHBOR,
                                   timeout=timeout)
    if not ok:
        return False, {"error": "solo run failed", "tail": tail}

    root = os.path.join(tmpdir, "pod")
    mgr = _manager(root, [
        TenantSpec(TENANT_INJECTED,
                   _demo_cmd("--crash-at", str(SCENARIO_TENANT_CRASH_AT))),
        TenantSpec(TENANT_NEIGHBOR, _demo_cmd()),
    ])
    digests = mgr.run()
    da = digests[TENANT_INJECTED]
    db = digests[TENANT_NEIGHBOR]
    meta_a = _tenant_out_meta(mgr, TENANT_INJECTED)
    recovery = _recovery(mgr.journal_path(TENANT_INJECTED))
    neighbor_recovery = _recovery(mgr.journal_path(TENANT_NEIGHBOR))
    audit = _audit(root)
    bit_identical = _bit_identical(
        solo_out, mgr.paths[TENANT_NEIGHBOR].out_path)
    detail = {
        "injected": {k: da.get(k) for k in
                     ("success", "attempts", "restarts", "quarantined")},
        "injected_skipped": meta_a.get("skipped"),
        "neighbor": {k: db.get(k) for k in
                     ("success", "attempts", "restarts", "quarantined")},
        "neighbor_bit_identical": bit_identical,
        "fence_epochs": {n: mgr.fence_epoch(n)
                         for n in (TENANT_INJECTED, TENANT_NEIGHBOR)},
        "recovery": recovery,
        "time_to_recovered_s": recovery["time_to_recovered_s"],
        "namespace_audit": audit,
    }
    ok = (bool(da.get("success")) and da.get("restarts") == 2
          and da.get("quarantined") == [SCENARIO_TENANT_CRASH_AT]
          and meta_a.get("skipped") == [SCENARIO_TENANT_CRASH_AT]
          and bool(db.get("success")) and db.get("restarts") == 0
          and db.get("quarantined") == []
          and neighbor_recovery["count"] == 0
          and detail["fence_epochs"] == {TENANT_INJECTED: 1,
                                         TENANT_NEIGHBOR: 1}
          and recovery["count"] >= 1
          and all(t > 0 for t in recovery["times_s"])
          and audit["clean"]
          and bit_identical)
    return ok, detail


def run_tenant_enospc_brownout_scenario(tmpdir: str, *,
                                        timeout: float = 600):
    """ENOSPC brownout CONFINED to one tenant's namespace: tenant ``a``
    carries a deterministic faultfs schedule in its spec env (the ONLY
    injection channel the manager offers — per-tenant by construction)
    failing a run of its snapshot writes with ENOSPC past the retry
    budget; tenant ``b`` runs fault-free beside it. The contract:

    * ``a`` SURVIVES WITHOUT A RESTART — storage faults cost recency,
      never state: at least one of its publishes degrades (skipped,
      ``storage.degraded_publishes`` counted in ``a``'s OWN telemetry)
      and its final weights still match the fault-free solo run;
    * ``b`` sees NONE of it: zero degraded publishes in its telemetry,
      zero restarts, weights bit-identical to solo;
    * the namespace audit is clean — a brownout inside ``a``'s
      checkpoint dir never wrote a byte anywhere else.
    """
    from fps_tpu.obs import fleet as obs_fleet
    from fps_tpu.tenancy import TenantSpec
    from fps_tpu.testing.faultfs import FAULTFS_ENV, FaultFS, FaultRule

    ok, solo_out, tail = _solo_run(tmpdir, TENANT_NEIGHBOR,
                                   timeout=timeout)
    if not ok:
        return False, {"error": "solo run failed", "tail": tail}

    schedule = FaultFS([FaultRule(
        "snapshot", "write", "errno", errno_name="ENOSPC",
        start=SCENARIO_TENANT_ENOSPC_START,
        count=SCENARIO_TENANT_ENOSPC_COUNT)], seed=0)
    root = os.path.join(tmpdir, "pod")
    mgr = _manager(root, [
        TenantSpec(TENANT_INJECTED, _demo_cmd(),
                   env={FAULTFS_ENV: schedule.to_spec()}),
        TenantSpec(TENANT_NEIGHBOR, _demo_cmd()),
    ])
    digests = mgr.run()
    da = digests[TENANT_INJECTED]
    db = digests[TENANT_NEIGHBOR]

    def _degraded(name):
        roll = obs_fleet.rollup([mgr.paths[name].obs_dir])
        return int(roll.get("totals", {}).get("degraded_publishes", 0))

    degraded_a = _degraded(TENANT_INJECTED)
    degraded_b = _degraded(TENANT_NEIGHBOR)
    audit = _audit(root)
    bit_a = _bit_identical(solo_out, mgr.paths[TENANT_INJECTED].out_path)
    bit_b = _bit_identical(solo_out, mgr.paths[TENANT_NEIGHBOR].out_path)
    detail = {
        "injected": {k: da.get(k) for k in
                     ("success", "restarts", "quarantined")},
        "neighbor": {k: db.get(k) for k in
                     ("success", "restarts", "quarantined")},
        "degraded_publishes": {TENANT_INJECTED: degraded_a,
                               TENANT_NEIGHBOR: degraded_b},
        "injected_bit_identical": bit_a,
        "neighbor_bit_identical": bit_b,
        "namespace_audit": audit,
        "time_to_recovered_s": None,  # survived in place: no restart
    }
    ok = (bool(da.get("success")) and da.get("restarts") == 0
          and da.get("quarantined") == []
          and bool(db.get("success")) and db.get("restarts") == 0
          and degraded_a >= 1 and degraded_b == 0
          and bit_a and bit_b
          and audit["clean"])
    return ok, detail


def run_tenant_reader_wedge_scenario(tmpdir: str, *, timeout: float = 600):
    """One tenant's WEDGED serving reader restarts without touching its
    neighbor's fences: two tenant namespaces each carry their own
    single-reader fleet (heartbeating reader child per namespace);
    tenant ``a``'s reader is SIGSTOPped mid-run, detected wedged via
    ``a``'s OWN liveness beacons, killed and relaunched — and the whole
    episode must be invisible from ``b``'s namespace:

    * ``a``'s wedge is detected within the liveness timeout; during the
      whole detection window ``b``'s reader never reports wedged (no
      cross-tenant false positives);
    * the restarted ``a`` reader catches up to ``a``'s newest
      publication — ``time_to_recovered_s`` measured SIGSTOP → caught
      up;
    * ``b``'s serve fence file is BYTE-IDENTICAL before and after the
      episode, and ``b``'s subsequent training + serving converge
      normally;
    * both tenants' final weights are bit-identical to the clean
      (reader-free) run of the same workload; the namespace audit is
      clean.
    """
    import signal
    import subprocess as sp
    import time as _time

    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.serve import liveness_check, scan_heartbeats
    from fps_tpu.serve.fleet import FENCE_NAME
    from fps_tpu.tenancy import TenantPaths
    from fps_tpu.testing.workloads import weights

    LIVENESS = 1.5
    _mesh, chunks, make_trainer = sd._storage_harness()

    # Clean arm (no readers, no tenancy): the bit-identity reference.
    trainer, store, tables, ls = make_trainer()
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))
    want_w = weights(store).copy()

    root = os.path.join(tmpdir, "pod")
    tpa = TenantPaths(root, TENANT_INJECTED).ensure()
    tpb = TenantPaths(root, TENANT_NEIGHBOR).ensure()
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT

    def _reader(ckpt_dir, rid):
        return sp.Popen([sys.executable, "-c", sd._READER_LOOP_SRC,
                         ckpt_dir, rid], env=env, cwd=_ROOT,
                        stdout=sp.DEVNULL, stderr=sp.DEVNULL)

    def _fence_bytes(ckpt_dir):
        # Raw bytes on purpose: the assertion is "this FILE never
        # changed", not a parsed read.
        try:
            with open(os.path.join(ckpt_dir, "fleet", FENCE_NAME),  # noqa: FPS006
                      "rb") as f:
                return f.read()
        except OSError:
            return None

    ra = _reader(tpa.ckpt_dir, "ra")
    rb = _reader(tpb.ckpt_dir, "rb")
    detail: dict = {}
    try:
        # Both readers must be demonstrably LIVE before any fault lands.
        dl = _time.monotonic() + 60.0
        while _time.monotonic() < dl:
            if (scan_heartbeats(tpa.ckpt_dir).get("ra")
                    and scan_heartbeats(tpb.ckpt_dir).get("rb")):
                break
            _time.sleep(0.05)
        else:
            return False, {"error": "readers never came up"}

        # Train tenant a; SIGSTOP its reader mid-run.
        stopped_at = [None]
        live_before = [None]

        def on_chunk(step, _metrics):
            if step != 4 or stopped_at[0] is not None:
                return
            live_before[0] = liveness_check(
                tpa.ckpt_dir, timeout_s=LIVENESS, expected=["ra"])
            os.kill(ra.pid, signal.SIGSTOP)
            stopped_at[0] = _time.monotonic()

        trainer, store, tables, ls = make_trainer()
        cka = AsyncCheckpointer(tpa.ckpt_dir, keep=len(chunks) + 2)
        tables, ls, _ = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1),
            checkpointer=cka, checkpoint_every=1, on_chunk=on_chunk)
        cka.flush()
        final_a = cka.latest_valid_step()
        cka.close()
        got_a = weights(store).copy()
        if stopped_at[0] is None:
            return False, {"error": "reader a was never SIGSTOPped"}
        b_fence_before = _fence_bytes(tpb.ckpt_dir)

        # a's wedge becomes an incident in a's OWN beacons; b's reader
        # must never read as wedged while we watch.
        wedged_at = None
        neighbor_false_positives = []
        dl = _time.monotonic() + min(timeout, 60.0)
        while _time.monotonic() < dl:
            live_b = liveness_check(tpb.ckpt_dir, timeout_s=LIVENESS,
                                    expected=["rb"])
            if live_b["wedged"]:
                neighbor_false_positives.append(live_b)
            live_a = liveness_check(tpa.ckpt_dir, timeout_s=LIVENESS,
                                    expected=["ra"])
            if "ra" in live_a["wedged"]:
                wedged_at = _time.monotonic()
                break
            _time.sleep(0.05)
        if wedged_at is None:
            return False, {"error": "reader_wedged never fired for a",
                           "heartbeats": scan_heartbeats(tpa.ckpt_dir)}
        detect_s = wedged_at - stopped_at[0]

        # Restart a's reader: kill the wedged child, relaunch the same
        # id — the episode's remedy, confined to a's namespace.
        ra.kill()
        ra.wait(timeout=10)
        ra = _reader(tpa.ckpt_dir, "ra")
        recovered_at = None
        dl = _time.monotonic() + min(timeout, 60.0)
        while _time.monotonic() < dl:
            live_a = liveness_check(tpa.ckpt_dir, timeout_s=LIVENESS,
                                    expected=["ra"])
            hb = scan_heartbeats(tpa.ckpt_dir).get("ra")
            if ("ra" not in live_a["wedged"] and hb is not None
                    and hb.get("step") == final_a):
                recovered_at = _time.monotonic()
                break
            _time.sleep(0.05)
        if recovered_at is None:
            return False, {"error": "restarted reader a never caught up",
                           "heartbeats": scan_heartbeats(tpa.ckpt_dir)}
        ttr = recovered_at - stopped_at[0]
        b_fence_after = _fence_bytes(tpb.ckpt_dir)

        # b's life goes on: train it now; its reader converges on its
        # own publications.
        trainer, store, tables, ls = make_trainer()
        ckb = AsyncCheckpointer(tpb.ckpt_dir, keep=len(chunks) + 2)
        tables, ls, _ = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1),
            checkpointer=ckb, checkpoint_every=1)
        ckb.flush()
        final_b = ckb.latest_valid_step()
        ckb.close()
        got_b = weights(store).copy()
        b_caught_up = False
        dl = _time.monotonic() + min(timeout, 60.0)
        while _time.monotonic() < dl:
            hb = scan_heartbeats(tpb.ckpt_dir).get("rb")
            if hb is not None and hb.get("step") == final_b:
                b_caught_up = True
                break
            _time.sleep(0.05)
    finally:
        for child in (ra, rb):
            child.kill()
            child.wait(timeout=10)

    audit = _audit(root)
    detail = {
        "live_before_stop": live_before[0],
        "wedge_detect_s": round(detect_s, 3),
        "time_to_recovered_s": round(ttr, 3),
        "neighbor_false_positives": neighbor_false_positives,
        "neighbor_fence_unchanged": b_fence_before == b_fence_after,
        "neighbor_caught_up": b_caught_up,
        "weights_bit_identical": {
            TENANT_INJECTED: bool(np.array_equal(got_a, want_w)),
            TENANT_NEIGHBOR: bool(np.array_equal(got_b, want_w)),
        },
        "namespace_audit": audit,
    }
    ok = (live_before[0] is not None
          and live_before[0]["wedged"] == []      # no false positive
          and detect_s < 30.0
          and not neighbor_false_positives
          and detail["neighbor_fence_unchanged"]
          and b_caught_up
          and all(detail["weights_bit_identical"].values())
          and audit["clean"])
    return ok, detail


def run_tenant_noisy_neighbor_scenario(tmpdir: str, *,
                                       timeout: float = 600):
    """Noisy-neighbor hot-tier pressure degrades ONLY the over-weight
    tenant. Two legs:

    * **arbitration leg** (pure planner arithmetic): tenant ``a``'s
      flat, huge access profile demands more replica budget than its
      fair share; ``b``'s concentrated profile demands far less.
      :func:`~fps_tpu.tiering.planner.plan_tenants` must grant ``b``
      its FULL demand — ``b``'s plan knobs identical to its solo
      (whole-budget) plan — while ``a`` is granted less than its demand
      and lands on a smaller hot tier than it would solo;
    * **training leg** (real children under the manager): both tenants
      train with the knobs the arbitration chose. Because ``b``'s knobs
      are the solo knobs BY CONSTRUCTION, ``b``'s final weights must be
      bit-identical to its solo run at those knobs; ``a`` (squeezed but
      functional) must still finish cleanly. Namespace audit clean.
    """
    import numpy as np

    from fps_tpu.tenancy import TenantSpec
    from fps_tpu.tiering.planner import (
        TableDensity,
        plan_tables,
        plan_tenants,
    )

    nf, dim = SCENARIO_TENANT_NN_NF, SCENARIO_TENANT_NN_DIM
    counts_a = np.full(nf, 5.0)                  # flat: wants ~all rows
    counts_b = np.zeros(nf)
    counts_b[:64] = 1000.0                       # concentrated head
    dens_a = [TableDensity("weights", nf, dim, counts_a)]
    dens_b = [TableDensity("weights", nf, dim, counts_b)]
    total = SCENARIO_TENANT_NN_BUDGET
    # dense_table_bytes=1024 keeps the table out of the replicate-dense
    # fast path so the coverage-head arbitration is actually exercised.
    plan_kw = dict(batch_rows_per_step=256, dense_table_bytes=1024)

    solo_a = plan_tables(dens_a, replica_budget_bytes=total,
                         **plan_kw)["weights"]
    solo_b = plan_tables(dens_b, replica_budget_bytes=total,
                         **plan_kw)["weights"]
    multi = plan_tenants(
        {TENANT_INJECTED: dens_a, TENANT_NEIGHBOR: dens_b},
        weights={TENANT_INJECTED: 1.0, TENANT_NEIGHBOR: 1.0},
        total_replica_budget_bytes=total, **plan_kw)
    ma, mb = multi[TENANT_INJECTED], multi[TENANT_NEIGHBOR]
    plan_a, plan_b = ma["plans"]["weights"], mb["plans"]["weights"]
    arbitration_ok = (
        plan_b.knobs() == solo_b.knobs()
        and mb["granted"] == mb["demand"]
        and ma["granted"] < ma["demand"]
        and 0 < plan_a.hot_tier < solo_a.hot_tier)
    arbitration = {
        "demand": {TENANT_INJECTED: ma["demand"],
                   TENANT_NEIGHBOR: mb["demand"]},
        "granted": {TENANT_INJECTED: ma["granted"],
                    TENANT_NEIGHBOR: mb["granted"]},
        "hot_rows": {TENANT_INJECTED: [solo_a.hot_tier, plan_a.hot_tier],
                     TENANT_NEIGHBOR: [solo_b.hot_tier, plan_b.hot_tier]},
    }
    if not arbitration_ok:
        return False, {"error": "arbitration leg failed",
                       "arbitration": arbitration}

    # Training leg: the arbitrated knobs drive real children. b's solo
    # arm runs at the SAME knobs the arbitration granted it (== its solo
    # plan), so bit-identity is the isolation claim, not luck.
    def _tier_args(plan):
        return ("--num-features", str(nf),
                "--hot-tier", str(plan.hot_tier),
                "--hot-sync-every", str(plan.hot_sync_every),
                "--cold-budget", str(plan.cold_budget))

    tier_a, tier_b = _tier_args(plan_a), _tier_args(plan_b)
    ok, solo_out, tail = _solo_run(tmpdir, TENANT_NEIGHBOR, *tier_b,
                                   timeout=timeout)
    if not ok:
        return False, {"error": "solo run failed", "tail": tail}

    root = os.path.join(tmpdir, "pod")
    mgr = _manager(root, [
        TenantSpec(TENANT_INJECTED, _demo_cmd(*tier_a), weight=1.0),
        TenantSpec(TENANT_NEIGHBOR, _demo_cmd(*tier_b), weight=1.0),
    ])
    digests = mgr.run()
    da = digests[TENANT_INJECTED]
    db = digests[TENANT_NEIGHBOR]
    audit = _audit(root)
    bit_b = _bit_identical(solo_out, mgr.paths[TENANT_NEIGHBOR].out_path)
    detail = {
        "arbitration": arbitration,
        "injected": {k: da.get(k) for k in ("success", "restarts")},
        "neighbor": {k: db.get(k) for k in ("success", "restarts")},
        "neighbor_bit_identical": bit_b,
        "namespace_audit": audit,
        "time_to_recovered_s": None,  # degradation, not an outage
    }
    ok = (arbitration_ok
          and bool(da.get("success")) and da.get("restarts") == 0
          and bool(db.get("success")) and db.get("restarts") == 0
          and bit_b
          and audit["clean"])
    return ok, detail
