"""faultnet — deterministic, seed-replayable NETWORK fault injection.

The hostile-network half of the chaos harness (``docs/resilience.md``
"Hostile network"), and the exact sibling of
:mod:`fps_tpu.testing.faultfs`: :class:`FaultNet` interposes on the
framework's socket operations through the
:func:`fps_tpu.core.retry.net_fault_check` seam (client connect/send/
recv in :class:`~fps_tpu.serve.wire.WireClient`, server accept/send in
:class:`~fps_tpu.serve.net.TcpServe`) — NEVER by global monkeypatching,
so only the framework's own wire traffic is ever faulted. Schedules are
stated in the wire plane's vocabulary: *peer classes* (``serve`` for
query traffic, ``fleet`` for reader-side sockets) crossed with
*operations* (``connect`` / ``accept`` / ``send`` / ``recv``).

Fault types (:class:`NetFaultRule.fault`):

* ``"refuse"``    — connect seams raise ``ConnectionRefusedError``
  (server down / port closed);
* ``"reset"``     — raise ``ConnectionResetError`` (peer died
  mid-conversation);
* ``"delay"``     — sleep ``delay_s`` before the operation proceeds
  (congested path, slow peer);
* ``"cut"``       — send seams transmit only ``cut_bytes`` of the frame
  and then drop the connection: the torn-frame producer the framing
  CRC/length gates must catch;
* ``"partition"`` — recv seams raise ``TimeoutError`` (a one-way
  partition: our bytes leave, theirs never arrive);
* ``"drop"``      — accept seams close the fresh connection unserved
  (SYN accepted, then silence);
* ``"trickle"``   — send seams emit the frame ``chunk`` bytes at a time
  with ``delay_s`` between chunks (slow-peer byte-trickle that holds a
  naive reader hostage).

Scheduling is **per (peer_class, op) operation count**, identical to
faultfs: each matching operation increments a deterministic counter and
a rule fires for counts in ``[start, start + count)`` hitting
``every``-th occurrence (``count=None`` = forever); an optional ``prob``
is still REPLAYABLE via ``sha256(seed, class, op, n)``. Same seed, same
op stream, same faults, every run — the determinism the bit-identity
chaos assertions stand on.

Cross-process: :meth:`FaultNet.to_env` serializes the schedule into
``FPS_TPU_FAULTNET`` and :func:`fps_tpu.core.retry.get_net_injector`
self-installs it lazily in any child (supervised training children,
jax-free serving processes).

Stdlib-only, like the seams it feeds.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import hashlib
import json
import os
import threading
import time

__all__ = ["NetFaultRule", "FaultNet", "install", "uninstall"]

# Mirror of fps_tpu.core.retry.FAULTNET_ENV (this module must stay
# loadable by file path with zero package imports — the env-activation
# path in retry.get_net_injector does exactly that; mirror-tested).
FAULTNET_ENV = "FPS_TPU_FAULTNET"

OPS = ("connect", "accept", "send", "recv")
FAULTS = ("refuse", "reset", "delay", "cut", "partition", "drop",
          "trickle")

# Which ops each fault makes sense on; a rule targeting an op its fault
# cannot express is a schedule bug, rejected at construction.
_FAULT_OPS = {
    "refuse": ("connect",),
    "reset": ("connect", "send", "recv"),
    "delay": OPS,
    "cut": ("send",),
    "partition": ("recv",),
    "drop": ("accept",),
    "trickle": ("send",),
}


@dataclasses.dataclass(frozen=True)
class NetFaultRule:
    """One scheduled wire fault: which (peer_class, op) stream it
    targets and which occurrences it hits. ``peer_class``/``op`` accept
    ``"*"`` (a ``"*"`` op is only legal for faults valid on every op,
    i.e. ``delay``)."""

    peer_class: str
    op: str
    fault: str
    delay_s: float = 0.0
    cut_bytes: int = 8
    chunk: int = 1
    start: int = 0
    count: int | None = 1
    every: int = 1
    prob: float = 1.0

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(
                f"fault must be one of {FAULTS}, got {self.fault!r}")
        if self.op != "*" and self.op not in OPS:
            raise ValueError(f"op must be one of {OPS} or '*', "
                             f"got {self.op!r}")
        legal = _FAULT_OPS[self.fault]
        if self.op == "*":
            if legal != OPS:
                raise ValueError(
                    f"fault {self.fault!r} only applies to ops {legal}; "
                    f"op='*' is ambiguous")
        elif self.op not in legal:
            raise ValueError(
                f"fault {self.fault!r} cannot fire on op {self.op!r} "
                f"(legal: {legal})")
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if not 0.0 < self.prob <= 1.0:
            raise ValueError(f"prob must be in (0, 1], got {self.prob}")
        if self.cut_bytes < 0 or self.chunk < 1:
            raise ValueError("cut_bytes must be >= 0 and chunk >= 1")

    def matches(self, cls: str, op: str, n: int, seed: int) -> bool:
        """Does this rule fire for occurrence ``n`` (0-based) of
        ``(cls, op)``? Pure function of the schedule — replayable."""
        if self.peer_class != "*" and self.peer_class != cls:
            return False
        if self.op != "*" and self.op != op:
            return False
        if n < self.start:
            return False
        if self.count is not None and n >= self.start + self.count:
            return False
        if (n - self.start) % self.every:
            return False
        if self.prob < 1.0:
            h = hashlib.sha256(
                f"{seed}:{cls}:{op}:{n}".encode()).digest()
            if int.from_bytes(h[:8], "big") / float(1 << 64) >= self.prob:
                return False
        return True

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class FaultNet:
    """The injector the :func:`fps_tpu.core.retry.net_fault_check` seam
    consults. Deterministic per-(class, op) counters; thread-safe (the
    server's accept/handler threads and any number of client threads
    cross the seams concurrently). ``injected`` accumulates an evidence
    trail ``(class, op, n, fault)`` the scenarios assert on."""

    def __init__(self, rules, *, seed: int = 0, sleep=time.sleep):
        self.rules = tuple(rules)
        self.seed = int(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counts: dict[tuple, int] = {}
        self.injected: list[tuple] = []

    # -- seam entry ---------------------------------------------------------

    def check(self, op: str, cls: str):
        with self._lock:
            n = self._counts.get((cls, op), 0)
            self._counts[(cls, op)] = n + 1
            rule = next((r for r in self.rules
                         if r.matches(cls, op, n, self.seed)), None)
            if rule is not None:
                self.injected.append((cls, op, n, rule.fault))
        if rule is None:
            return None
        # Side effects OUTSIDE the lock: sleeping under it would
        # serialize every connection behind one injected latency.
        if rule.fault == "delay":
            self._sleep(rule.delay_s)
            return None
        if rule.fault == "refuse":
            raise ConnectionRefusedError(
                _errno.ECONNREFUSED, "faultnet injected connection "
                f"refused ({cls}/{op} #{n})")
        if rule.fault == "reset":
            if rule.delay_s > 0:
                self._sleep(rule.delay_s)
            raise ConnectionResetError(
                _errno.ECONNRESET,
                f"faultnet injected connection reset ({cls}/{op} #{n})")
        if rule.fault == "partition":
            if rule.delay_s > 0:
                self._sleep(rule.delay_s)
            raise TimeoutError(
                f"faultnet injected one-way partition ({cls}/{op} #{n})")
        if rule.fault == "cut":
            return ("cut", rule.cut_bytes)
        if rule.fault == "trickle":
            return ("trickle", rule.chunk, rule.delay_s)
        return "drop"  # accept seams close the connection unserved

    # -- evidence -----------------------------------------------------------

    def injected_counts(self) -> dict:
        """``{(class, op, fault): n}`` totals — scenario evidence."""
        out: dict[tuple, int] = {}
        with self._lock:
            for cls, op, _, fault in self.injected:
                key = (cls, op, fault)
                out[key] = out.get(key, 0) + 1
        return out

    def trail(self) -> list[tuple]:
        """A snapshot copy of the evidence trail (determinism tests
        compare two runs' trails for equality)."""
        with self._lock:
            return list(self.injected)

    def quiesce(self) -> None:
        """Drop every rule (the network 'heals') while keeping counters
        and the evidence trail — the recovery half of a brownout."""
        self.rules = ()

    def close(self) -> None:
        pass  # symmetric with FaultFS.close for uninstall()

    # -- (de)serialization (the cross-process env contract) -----------------

    def to_spec(self) -> str:
        return json.dumps({"seed": self.seed,
                           "rules": [r.to_json() for r in self.rules]})

    def to_env(self, env: dict | None = None) -> dict:
        env = dict(os.environ if env is None else env)
        env[FAULTNET_ENV] = self.to_spec()
        return env

    @classmethod
    def from_spec(cls, spec: str) -> "FaultNet":
        """Build from a JSON spec string or a path to a spec file (the
        two forms ``FPS_TPU_FAULTNET`` accepts)."""
        text = spec
        if not spec.lstrip().startswith("{"):
            with open(spec, encoding="utf-8") as f:
                text = f.read()
        obj = json.loads(text)
        return cls([NetFaultRule(**r) for r in obj.get("rules", ())],
                   seed=int(obj.get("seed", 0)))


def install(rules, *, seed: int = 0, sleep=time.sleep) -> FaultNet:
    """Build + install a :class:`FaultNet` as the process net injector."""
    from fps_tpu.core import retry as _retry

    net = FaultNet(rules, seed=seed, sleep=sleep)
    _retry.install_net_injector(net)
    return net


def uninstall() -> None:
    from fps_tpu.core import retry as _retry

    inj = _retry.get_net_injector()
    _retry.remove_net_injector()
    if inj is not None and hasattr(inj, "close"):
        inj.close()
