"""Test-support utilities shipped with the framework (fault injection)."""

from fps_tpu.testing.chaos import (  # noqa: F401
    bitflip_file,
    corrupt_latest_snapshot,
    kill_at_epoch,
    partial_write_then_kill,
    poison_chunks,
    poison_rows,
    sigkill_self,
    truncate_file,
)
