"""Tiny deterministic workloads shared by the resilience tests
(``tests/test_resilience.py``) and the chaos sweep
(``tools/chaos_sweep.py``) — one copy of the harness, so a change to the
guard API or the health-channel layout cannot silently drift between the
two consumers.

Everything here is seed-pinned: same mesh + same calls ⇒ bit-identical
runs (the property several resilience tests assert on).
"""

from __future__ import annotations

import numpy as np

import jax

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import multi_epoch_chunks
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
    predict_proba_host,
)
from fps_tpu.utils.datasets import (
    synthetic_sparse_classification,
    train_test_split,
)

# Small enough that every route stays fast on the CPU test mesh, big
# enough that the planted structure is clearly learnable (acc >~ 0.75).
NF, NNZ = 400, 8


def logreg_data(num_examples: int = 4000):
    """(train, test) split of the planted sparse-classification set."""
    data = synthetic_sparse_classification(num_examples, NF, NNZ, seed=7,
                                           noise=0.05)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))
    return train_test_split(data)


def logreg_chunks(train, num_workers: int, epochs: int = 3):
    return list(
        multi_epoch_chunks(
            train, epochs, num_workers=num_workers, local_batch=32,
            steps_per_chunk=8, seed=3,
        )
    )


def run_logreg(mesh, chunks, *, guard=None, rollback=None):
    """Train the standard tiny logreg over ``chunks``; returns
    ``(trainer, store, per-chunk metrics list)``."""
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg, guard=guard)
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.fit_stream(
        tables, ls, iter(chunks), jax.random.key(1), rollback=rollback
    )
    return trainer, store, m


def accuracy(store, test) -> float:
    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    return float(np.mean((p > 0.5) == (test["label"] > 0.5)))


def weights(store) -> np.ndarray:
    return store.lookup_host("weights", np.arange(NF))


def health_sum(metrics, table: str, kind: str) -> int:
    """Total of one health counter over a run's per-chunk metrics list."""
    return sum(
        int(np.sum(np.asarray(m["health"][table][kind])))
        for m in metrics
        if "health" in m
    )
