"""faultfs — deterministic, seed-replayable I/O fault injection.

The hostile-filesystem half of the chaos harness (``docs/resilience.md``
"Hostile filesystem"): :class:`FaultFS` interposes on the framework's
file operations through the seams in :mod:`fps_tpu.core.retry`
(``_atomic_savez``, snapshot reads, lease/fence writes, sidecar writes,
directory scans) — NEVER by global monkeypatching, so only the
framework's own storage traffic is ever faulted and the schedule is
stated in the framework's vocabulary: *path classes* (``snapshot`` /
``lease`` / ``fence`` / ``sidecar`` / ``control`` / ``journal``) crossed
with *operations* (``write`` / ``fsync`` / ``replace`` / ``read`` /
``listdir`` / ``remove``).

Fault types (:class:`FaultRule.fault`):

* ``"errno"``  — raise ``OSError(errno_name)`` (ENOSPC, EIO, ETIMEDOUT,
  transient ENOENT, ...);
* ``"delay"``  — sleep ``delay_s`` before the operation proceeds (slow
  write / slow fsync / storage brownout latency);
* ``"torn"``   — rename seams publish a truncated prefix of the tmp
  file at the destination and then fail with EIO: the torn-publish the
  CRC gates must catch;
* ``"stale"``  — read seams are redirected to the PRE-rename content of
  the path (captured by the injector when it sees the ``replace``), the
  stale read-after-rename of a caching network filesystem; with no
  shadow captured yet it degrades to a transient ENOENT.

Scheduling is **per (path_class, op) operation count**: each matching
operation increments a deterministic counter, and a rule fires for
counts in ``[start, start + count)`` hitting ``every``-th occurrence
(``count=None`` = forever). An optional ``prob`` makes a rule
probabilistic but still REPLAYABLE: the decision is
``sha256(seed, class, op, n)``, a pure function of the schedule seed and
the op index — same seed, same op stream, same faults, every run.

Cross-process: :meth:`FaultFS.to_env` serializes the schedule into the
``FPS_TPU_FAULTFS`` env var (or a spec file path) and
:func:`fps_tpu.core.retry.get_injector` self-installs it lazily in any
child process — supervised training children, pod agents, and jax-free
serving processes all honor one schedule format.

Stdlib-only, like the seams it feeds.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import hashlib
import json
import os
import shutil
import tempfile
import threading
import time

__all__ = ["FaultRule", "FaultFS", "install", "uninstall"]

# Mirror of fps_tpu.core.retry.FAULTFS_ENV (this module must stay
# loadable by file path with zero package imports — the env-activation
# path in retry.get_injector does exactly that; mirror-tested).
FAULTFS_ENV = "FPS_TPU_FAULTFS"

OPS = ("write", "fsync", "replace", "read", "listdir", "remove")
FAULTS = ("errno", "delay", "torn", "stale")


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One scheduled fault: which (path_class, op) stream it targets and
    which occurrences it hits. ``path_class``/``op`` accept ``"*"``."""

    path_class: str
    op: str
    fault: str
    errno_name: str = "EIO"
    delay_s: float = 0.0
    start: int = 0
    count: int | None = 1
    every: int = 1
    prob: float = 1.0

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(
                f"fault must be one of {FAULTS}, got {self.fault!r}")
        if self.op != "*" and self.op not in OPS:
            raise ValueError(f"op must be one of {OPS} or '*', "
                             f"got {self.op!r}")
        if self.fault == "errno" and not hasattr(_errno,
                                                 self.errno_name):
            raise ValueError(f"unknown errno name {self.errno_name!r}")
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if not 0.0 < self.prob <= 1.0:
            raise ValueError(f"prob must be in (0, 1], got {self.prob}")

    def matches(self, cls: str, op: str, n: int, seed: int) -> bool:
        """Does this rule fire for occurrence ``n`` (0-based) of
        ``(cls, op)``? Pure function of the schedule — replayable."""
        if self.path_class != "*" and self.path_class != cls:
            return False
        if self.op != "*" and self.op != op:
            return False
        if n < self.start:
            return False
        if self.count is not None and n >= self.start + self.count:
            return False
        if (n - self.start) % self.every:
            return False
        if self.prob < 1.0:
            h = hashlib.sha256(
                f"{seed}:{cls}:{op}:{n}".encode()).digest()
            if int.from_bytes(h[:8], "big") / float(1 << 64) >= self.prob:
                return False
        return True

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class FaultFS:
    """The injector the :func:`fps_tpu.core.retry.fault_check` seam
    consults. Deterministic per-(class, op) counters; thread-safe (the
    async checkpoint writer, the fleet pollers, and the training thread
    all cross the seams concurrently). ``injected`` accumulates an
    evidence trail ``(class, op, n, fault, basename)`` the scenarios
    assert on."""

    def __init__(self, rules, *, seed: int = 0, sleep=time.sleep):
        self.rules = tuple(rules)
        self.seed = int(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counts: dict[tuple, int] = {}
        self.injected: list[tuple] = []
        # Pre-rename shadows for "stale": path -> shadow copy of the
        # content that was live at the last faulted-window replace.
        self._shadow_dir: str | None = None
        self._shadows: dict[str, str] = {}
        self._shadow_seq = 0
        self._wants_stale = any(r.fault == "stale" for r in self.rules)

    # -- seam entry ---------------------------------------------------------

    def check(self, op: str, cls: str, path: str):
        with self._lock:
            n = self._counts.get((cls, op), 0)
            self._counts[(cls, op)] = n + 1
            rule = next((r for r in self.rules
                         if r.matches(cls, op, n, self.seed)), None)
            if rule is not None:
                self.injected.append(
                    (cls, op, n, rule.fault, os.path.basename(path)))
        if self._wants_stale and op == "replace":
            # Capture the pre-rename content so a later "stale" read
            # can serve it — whether or not THIS replace is itself
            # faulted. Outside the lock: copying a multi-MB snapshot
            # under it would serialize every plane behind the copy.
            self._capture_shadow(path)
        if rule is None:
            return None
        # Side effects OUTSIDE the lock: sleeping under it would
        # serialize every plane behind one injected latency.
        if rule.fault == "delay":
            self._sleep(rule.delay_s)
            return None
        if rule.fault == "errno":
            if rule.delay_s > 0:
                self._sleep(rule.delay_s)
            code = getattr(_errno, rule.errno_name)
            raise OSError(code, f"faultfs injected {rule.errno_name}",
                          path)
        if rule.fault == "torn":
            return "torn"
        # "stale": redirect reads to the pre-rename shadow when one was
        # captured; a not-yet-shadowed path degrades to the transient
        # ENOENT form of the same failure (the rename not visible yet).
        shadow = self._shadows.get(os.path.abspath(path))
        if shadow is not None and os.path.exists(shadow):
            return ("redirect", shadow)
        raise OSError(_errno.ENOENT,
                      "faultfs injected stale read (no shadow)", path)

    def _capture_shadow(self, path: str) -> None:
        try:
            if not os.path.exists(path):
                return
            with self._lock:
                if self._shadow_dir is None:
                    self._shadow_dir = tempfile.mkdtemp(
                        prefix="faultfs-")
                self._shadow_seq += 1
                name = f"{self._shadow_seq}-{os.path.basename(path)}"
                shadow = os.path.join(self._shadow_dir, name)
            # The copy itself runs UNLOCKED (see check()); only the
            # bookkeeping takes the lock, and the unique sequence
            # number keeps concurrent captures from clobbering.
            shutil.copyfile(path, shadow)
            with self._lock:
                self._shadows[os.path.abspath(path)] = shadow
        except OSError:
            pass  # best-effort: stale degrades to transient ENOENT

    # -- evidence -----------------------------------------------------------

    def injected_counts(self) -> dict:
        """``{(class, op, fault): n}`` totals — scenario evidence."""
        out: dict[tuple, int] = {}
        with self._lock:
            for cls, op, _, fault, _ in self.injected:
                key = (cls, op, fault)
                out[key] = out.get(key, 0) + 1
        return out

    def quiesce(self) -> None:
        """Drop every rule (storage 'recovers') while keeping counters
        and the evidence trail — the recovery half of a brownout."""
        self.rules = ()

    def close(self) -> None:
        if self._shadow_dir is not None:
            shutil.rmtree(self._shadow_dir, ignore_errors=True)
            self._shadow_dir = None

    # -- (de)serialization (the cross-process env contract) -----------------

    def to_spec(self) -> str:
        return json.dumps({"seed": self.seed,
                           "rules": [r.to_json() for r in self.rules]})

    def to_env(self, env: dict | None = None) -> dict:
        env = dict(os.environ if env is None else env)
        env[FAULTFS_ENV] = self.to_spec()
        return env

    @classmethod
    def from_spec(cls, spec: str) -> "FaultFS":
        """Build from a JSON spec string or a path to a spec file (the
        two forms ``FPS_TPU_FAULTFS`` accepts)."""
        text = spec
        if not spec.lstrip().startswith("{"):
            with open(spec, encoding="utf-8") as f:
                text = f.read()
        obj = json.loads(text)
        return cls([FaultRule(**r) for r in obj.get("rules", ())],
                   seed=int(obj.get("seed", 0)))


def install(rules, *, seed: int = 0, sleep=time.sleep) -> FaultFS:
    """Build + install a :class:`FaultFS` as the process injector."""
    from fps_tpu.core import retry as _retry

    fs = FaultFS(rules, seed=seed, sleep=sleep)
    _retry.install_injector(fs)
    return fs


def uninstall() -> None:
    from fps_tpu.core import retry as _retry

    inj = _retry.get_injector()
    _retry.remove_injector()
    if inj is not None and hasattr(inj, "close"):
        inj.close()
