"""Tiny supervised training child: the real-jax end of the supervisor story.

``python -m fps_tpu.testing.supervised_demo --ckpt-dir D --out W.npz ...``
runs the standard tiny logreg workload (:mod:`fps_tpu.testing.workloads`)
under the full supervised-child contract:

* resumes from ``latest_valid_step`` in ``--ckpt-dir`` (fresh process,
  the framework's kill-resume contract) with ``checkpoint_every=1``
  through an :class:`~fps_tpu.core.checkpoint.AsyncCheckpointer`;
* beats the supervisor heartbeat (env contract,
  :mod:`fps_tpu.supervise.child`) on every chunk boundary;
* preloads the supervisor-carried quarantine set into
  ``RollbackPolicy(preset=...)``;
* misbehaves on demand — ``--wedge-at K`` (SIGSTOP / sleep-forever after
  chunk K trains, BEFORE its checkpoint lands: exactly one chunk of work
  at risk) or ``--crash-at K`` (deterministic exit(3): the poison-crash
  loop the supervisor must quarantine through). Both are once-only via a
  marker file next to the checkpoints unless ``--always`` is given.

Deterministic end to end: a supervised wedged run must reproduce the
straight run's final weights BIT-FOR-BIT (asserted by
``tools/chaos_sweep.py``'s ``supervised`` scenario and the slow test in
``tests/test_supervise.py``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Shared by the straight and supervised runs of the scenario below —
# bit-identity only means something when both children run the exact same
# workload.
SCENARIO_DEMO_ARGS = ("--examples", "8000", "--epochs", "2")
SCENARIO_WEDGE_AT = 3
# Mid-prefetch SIGKILL scenario: pipeline depth and the chunk whose
# background assembly the child dies in.
SCENARIO_PREFETCH_DEPTH = 2
SCENARIO_PREFETCH_KILL_AT = 4
# Hot-tier kill scenario: two-tier storage config and the chunk boundary
# the child dies at (between hot-tier reconciles from the snapshot
# trail's point of view: the chunk's own boundary reconcile ran, its
# checkpoint never landed).
SCENARIO_HOT_TIER = 64
SCENARIO_HOT_SYNC = 3
SCENARIO_HOT_KILL_AT = 3
# Retier-kill scenario: ADAPTIVE tier (mapped hot set + online
# tracking, forced re-rank every check) killed between a re-rank and
# the next checkpoint. check_every=2 puts re-rank checks at chunk
# boundaries 1, 3, 5...; the kill at chunk 3 fires BEFORE boundary 3's
# retier runs, so the restart must restore the last reconciled
# snapshot AND the step-3 tracker sidecar, re-plan (re-derive the hot
# set / replica / slot map), and replay chunk 3 bit-identically.
SCENARIO_RETIER_EVERY = 2
SCENARIO_RETIER_KILL_AT = 3
# Sharded-reconcile kill scenario (PR 10): a FULLY-replicated hot tier
# with a stateful Adagrad server fold — its per-row optimizer state is
# sharded over the replica axis by the reduce-scatter reconcile and
# persisted as fold:: checkpoint arrays. The SIGKILL lands between a
# reduce-scatter window (the chunk's boundary flush-reconcile ran, its
# Adagrad state advanced) and the next checkpoint; the restart must
# restore canonical tables AND the matching fold state, or the resumed
# Adagrad trajectory diverges from the straight run.
SCENARIO_FOLD_TIER = 400  # >= NF: full replication (hot_fold requires it)
SCENARIO_FOLD_SYNC = 3
SCENARIO_FOLD_KILL_AT = 3


def run_supervised_scenario(tmpdir: str, *, timeout: float = 600):
    """THE end-to-end supervisor survival scenario, shared by
    ``tools/chaos_sweep.py`` (``supervised``) and the slow test in
    ``tests/test_supervise.py`` so the two cannot drift: SIGSTOP-wedge a
    real training child mid-run; the supervisor must deadline-abort
    (SIGTERM→SIGKILL), restart with backoff, resume from
    ``latest_valid_step`` (exactly one chunk replayed), select no corrupt
    snapshot, and reproduce the unsupervised straight run's final weights
    bit-for-bit.

    Returns ``(ok, detail)`` — ``detail`` carries the evidence either
    caller surfaces (supervisor digest excerpt, restored step, the
    bit-identity verdict, any ``*.corrupt`` files).
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "10",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--wedge-at", str(SCENARIO_WEDGE_AT), "--wedge-mode", "sigstop"],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("deadline_aborts") == 1
          and digest.get("restarts") == 1
          # The wedge fires after chunk SCENARIO_WEDGE_AT trains (with
          # the async writer flushed first), before its checkpoint
          # lands: latest_valid_step == SCENARIO_WEDGE_AT means at most
          # one chunk of work was lost and replayed.
          and meta.get("restored_step") == SCENARIO_WEDGE_AT
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_prefetch_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL mid-PREFETCH under the supervisor: the child runs with the
    overlapped host pipeline on (``--prefetch 2``) and dies while the
    background worker is assembling chunk ``SCENARIO_PREFETCH_KILL_AT``
    (once, marker-gated) — a death BETWEEN chunk boundaries, several
    chunks ahead of the one being dispatched. The supervisor must see the
    crash, restart with backoff, and the resumed attempt (pipeline still
    on, resuming from ``latest_valid_step``) must finish clean and
    reproduce a straight pipeline-on run's final weights bit-for-bit.
    A single crash must NOT quarantine anything (quarantine needs two
    consecutive deaths at one index).

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS, "--prefetch", str(SCENARIO_PREFETCH_DEPTH)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-prefetch-at", str(SCENARIO_PREFETCH_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    # Sub-phase attribution evidence: the killed attempt's record must
    # carry the sub-chunk boundary the child last crossed. Which one is
    # timing-dependent (the worker dies while the driver is at its own
    # boundary), but it must be one of the driver's phases, not null.
    import json as _json

    try:
        with open(os.path.join(sup_dir, "supervisor_state.json"),
                  encoding="utf-8") as f:
            attempts = _json.load(f).get("attempts", [])
        killed_phase = attempts[0].get("last_phase") if attempts else None
    except (OSError, _json.JSONDecodeError, IndexError):
        killed_phase = None
    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "bit_identical": bit_identical,
        "killed_attempt_phase": killed_phase,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          and killed_phase in ("prefetch", "ingest", "dispatch")
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_hot_tier_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between hot-tier reconciles under the supervisor: the
    child runs with two-tier storage on (``--hot-tier``/
    ``--hot-sync-every``, replicated head + per-device pending deltas)
    and dies at a chunk boundary before that chunk's checkpoint lands.
    The restart must restore from the last durable snapshot — by the
    flush-reconcile boundary invariant, always ONE canonical table with
    every hot push folded in — re-split the hot replica from it, and
    replay to final weights BIT-IDENTICAL to a straight (unkilled)
    tiered run. A single crash must not quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_HOT_TIER),
            "--hot-sync-every", str(SCENARIO_HOT_SYNC)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight tiered run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_HOT_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_HOT_KILL_AT trains (the
          # async writer flushed first) and before its checkpoint lands:
          # restored_step == SCENARIO_HOT_KILL_AT means exactly one chunk
          # was lost and replayed from a reconciled snapshot.
          and meta.get("restored_step") == SCENARIO_HOT_KILL_AT
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_reconcile_shard_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between a sharded (reduce-scatter) reconcile window and
    the next checkpoint, with a stateful Adagrad hot-tier fold on
    (``--hot-fold adagrad``: per-row optimizer state sharded over the
    replica axis, persisted as ``fold::`` checkpoint arrays beside —
    never inside — the canonical table bytes). The restart must restore
    the last durable snapshot's canonical tables AND its fold state and
    replay to final weights BIT-IDENTICAL to a straight (unkilled) run —
    a fold state restarted from zeros would re-derive different Adagrad
    step sizes and diverge. A single crash must not quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_FOLD_TIER),
            "--hot-sync-every", str(SCENARIO_FOLD_SYNC),
            "--hot-fold", "adagrad"]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight hot-fold run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_FOLD_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    # The snapshots really carry the sharded fold state as its own kind:
    # canonical table bytes stay untouched (untiered readers skip
    # fold::), and a resume without it could not be bit-identical.
    fold_persisted = False
    snaps = sorted(glob.glob(os.path.join(sup_dir, "ckpt_*.npz")))
    if snaps:
        with np.load(snaps[-1]) as z:
            fold_persisted = any(k.startswith("fold::") for k in z.files)
    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "fold_persisted": fold_persisted,
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_FOLD_KILL_AT trains (the
          # async writer flushed first) and before its checkpoint lands:
          # exactly one chunk lost, replayed from a snapshot holding
          # both the reconciled tables and the matching Adagrad state.
          and meta.get("restored_step") == SCENARIO_FOLD_KILL_AT
          and fold_persisted
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_retier_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between a hot-set re-rank and the next checkpoint, under
    the supervisor, with the ADAPTIVE tier on (``--hot-tier`` +
    ``--retier-every``: mapped hot set, device-side tracking, forced
    re-rank every check, tracker sidecars beside the checkpoints). The
    restart must restore the last reconciled snapshot (one canonical
    table — re-ranks never touch canonical rows), restore the matching
    tracker sidecar, re-derive the hot replica / slot map from both
    (``Trainer._attach_hot``), and replay to final weights BIT-IDENTICAL
    to a straight (unkilled) adaptive run — i.e. the resumed run's
    re-rank decisions are the straight run's. A single crash must not
    quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_HOT_TIER),
            "--hot-sync-every", str(SCENARIO_HOT_SYNC),
            "--retier-every", str(SCENARIO_RETIER_EVERY)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight adaptive run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(straight_out + ".meta.json", encoding="utf-8") as f:
            straight_meta = json.load(f)
    except OSError:
        straight_meta = {}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_RETIER_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "sidecar_restored": meta.get("tiering_restored"),
        "re_ranks": [straight_meta.get("re_ranks"), meta.get("re_ranks")],
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_RETIER_KILL_AT trains
          # (async writer flushed first), before its checkpoint lands.
          and meta.get("restored_step") == SCENARIO_RETIER_KILL_AT
          # The restart really restored the step-3 tracker sidecar —
          # without it the resumed re-rank decisions start cold and the
          # bit-identity below would be vacuous luck.
          and meta.get("tiering_restored") is True
          # The adaptive machinery actually exercised: the straight run
          # re-ranked at least once (forced-cadence mode re-ranks on the
          # first check; the resumed attempt's count may legitimately be
          # 0 on a stationary stream — its hot set is already ranked).
          and (straight_meta.get("re_ranks") or 0) >= 1
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


SCENARIO_SERVE_KILL_AT = 3


def run_serve_while_train_scenario(tmpdir: str, *, timeout: float = 600):
    """Serve-while-train survival (``fps_tpu.serve``, ``docs/serving.md``):
    a concurrent ReadServer polls a supervised child's checkpoint dir the
    whole run while the child is SIGKILLed after chunk
    ``SCENARIO_SERVE_KILL_AT`` trains (before its checkpoint lands) and a
    torn full-named snapshot candidate (a partial write that DID reach a
    published name) is planted mid-run. The read-path contract under test:

    * readers never observe a torn or CRC-failing table (the torn
      candidate is rejected, never served; every served pull returns
      finite rows from a verified snapshot);
    * the served step is monotone FORWARD for the whole (quarantine-free)
      run, kill and restart included, and ends on the newest valid
      snapshot with bytes equal to that snapshot's table;
    * when the final served snapshot is then quarantined (the trainer's
      ``*.corrupt`` rename), the reader swaps BACKWARD to the surviving
      snapshot — never keeps answering past the rollback.

    Returns ``(ok, detail)`` like the other scenarios; shared by
    ``tools/chaos_sweep.py`` (``serve_while_train``) and the slow test in
    ``tests/test_serve.py`` so the two cannot drift.
    """
    import subprocess as sp
    import time as _time

    import numpy as np

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.serve import ReadServer, SnapshotWatcher

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    sup_dir = os.path.join(tmpdir, "sup")
    sup_out = os.path.join(tmpdir, "sup.npz")
    proc = sp.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         sys.executable, "-m", "fps_tpu.testing.supervised_demo",
         *SCENARIO_DEMO_ARGS, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_SERVE_KILL_AT)],
        env=env, cwd=_ROOT, stdout=sp.PIPE, stderr=sp.PIPE, text=True,
    )

    server = ReadServer()
    swap_trail: list[tuple[str, int]] = []

    def on_swap(snap, direction):
        server.swap_to(snap)
        swap_trail.append((direction, snap.step))

    watcher = SnapshotWatcher(sup_dir, on_swap=on_swap)
    violations: list[str] = []
    served_steps: list[int] = []
    torn_planted = None
    deadline = _time.monotonic() + timeout
    while proc.poll() is None and _time.monotonic() < deadline:
        watcher.poll()
        snap = server._snap
        if snap is not None:
            step, rows = server.pull("weights", np.arange(
                snap.tables["weights"].shape[0]))
            if not np.all(np.isfinite(rows)):
                violations.append(f"non-finite rows served at step {step}")
            if served_steps and step < served_steps[-1]:
                violations.append(
                    f"served step went backward without a quarantine: "
                    f"{served_steps[-1]} -> {step}")
            served_steps.append(step)
            if torn_planted is None:
                # The partial-write injection that DID reach a published
                # name: a torn candidate NEWER than everything real. The
                # watcher must reject it and keep serving; the restarted
                # child's auto-resolve restore quarantines it.
                torn_planted = fmt.snapshot_path(sup_dir, snap.step + 50)
                with open(torn_planted, "wb") as f:
                    f.write(b"PK\x03\x04" + b"\xde\xad" * 512)
        _time.sleep(0.05)

    try:
        stdout, stderr = proc.communicate(timeout=max(
            5.0, deadline - _time.monotonic()))
    except sp.TimeoutExpired:
        proc.kill()
        return False, {"error": "supervised run timed out"}
    try:
        digest = json.loads(stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (stdout + stderr)[-1000:]}

    # Final convergence: the reader must end on the newest valid snapshot
    # with exactly its bytes.
    watcher.poll()
    final = fmt.latest_valid_snapshot(sup_dir)
    final_consistent = False
    if final is not None and server._snap is not None:
        want = fmt.map_snapshot_arrays(final[1])["table::weights"]
        _, got = server.pull("weights", np.arange(want.shape[0]))
        final_consistent = bool(server._snap.step == final[0]
                                and np.array_equal(got, want))

    # Rollback leg: quarantine the served snapshot the way the trainer
    # does (*.corrupt rename) — the reader must swap BACKWARD, not keep
    # answering from rolled-back-past state.
    backward_ok = False
    if server._snap is not None:
        quarantined_step = server._snap.step
        path = fmt.snapshot_path(sup_dir, quarantined_step)
        os.replace(path, path + ".corrupt")
        watcher.poll()
        snap = server._snap
        backward_ok = bool(snap is not None
                           and snap.step < quarantined_step
                           and swap_trail[-1][0] == "backward"
                           and np.all(np.isfinite(
                               server.pull("weights", [0, 1])[1])))

    detail = {
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "polls_served": len(served_steps),
        "served_step_span": ([served_steps[0], served_steps[-1]]
                             if served_steps else None),
        "swap_trail": swap_trail,
        "rejected_snapshots": watcher.rejected,
        "violations": violations,
        "final_consistent": final_consistent,
        "backward_swap_ok": backward_ok,
    }
    ok = bool(proc.returncode == 0 and digest.get("success")
              and digest.get("restarts") == 1
              and not violations
              and len(served_steps) > 0
              # The planted torn candidate was seen and refused.
              and watcher.rejected >= 1
              and final_consistent
              and backward_ok)
    return ok, detail


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="supervised tiny-logreg child (fps_tpu.supervise demo)")
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True,
                    help="final weights .npz (written on success)")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--examples", type=int, default=2000)
    ap.add_argument("--wedge-at", type=int, default=None,
                    help="wedge after this chunk trains, before its "
                         "checkpoint lands (once, via marker file)")
    ap.add_argument("--wedge-mode", default="sigstop",
                    choices=["sigstop", "sleep"])
    ap.add_argument("--crash-at", type=int, default=None,
                    help="exit(3) at this chunk on every attempt not "
                         "carrying it in the quarantine set")
    ap.add_argument("--always", action="store_true",
                    help="misbehave on every attempt (no marker)")
    ap.add_argument("--sync-checkpointer", action="store_true",
                    help="use the blocking Checkpointer instead of the "
                         "async writer")
    ap.add_argument("--prefetch", type=int, default=0,
                    help="overlapped host pipeline depth "
                         "(TrainerConfig.prefetch)")
    ap.add_argument("--kill-prefetch-at", type=int, default=None,
                    help="SIGKILL while the prefetch worker assembles "
                         "this (global) chunk index — once, via marker "
                         "file, unless --always")
    ap.add_argument("--kill-at", type=int, default=None,
                    help="SIGKILL after this chunk trains (async writer "
                         "flushed first), before its checkpoint lands — "
                         "once, via marker file, unless --always")
    ap.add_argument("--hot-tier", type=int, default=0,
                    help="two-tier storage: replicate the leading H ids "
                         "(TableSpec.hot_tier)")
    ap.add_argument("--hot-sync-every", type=int, default=1,
                    help="hot-tier reconcile cadence in steps "
                         "(TrainerConfig.hot_sync_every)")
    ap.add_argument("--retier-every", type=int, default=0,
                    help="adaptive tiering (fps_tpu.tiering): attach a "
                         "Retierer checking every N chunk boundaries "
                         "with FORCED re-ranks (churn threshold -1) and "
                         "tracker sidecars beside the checkpoints; "
                         "combine with --hot-tier/--hot-sync-every for "
                         "the mapped tier")
    ap.add_argument("--cold-budget", type=int, default=0,
                    help="payload-proportional cold routing "
                         "(TableSpec.cold_budget; needs a partial "
                         "--hot-tier)")
    ap.add_argument("--hot-fold", default=None,
                    choices=["adagrad", "adam"],
                    help="stateful hot-tier server optimizer "
                         "(ServerLogic.hot_fold; needs a fully-"
                         "replicated --hot-tier and --hot-sync-every "
                         "> 1) — its sharded state rides checkpoints "
                         "as fold:: arrays")
    args = ap.parse_args(argv)

    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer, Checkpointer
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.resilience import RollbackPolicy
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.supervise import child
    from fps_tpu.testing import chaos
    from fps_tpu.testing.workloads import (
        NF,
        logreg_chunks,
        logreg_data,
        weights,
    )

    hb = child.from_env()
    preset = child.quarantined_from_env()
    attempt = child.attempt_from_env()

    # A heartbeat-only recorder makes the DRIVER's sub-phase beats
    # (prefetch/ingest/dispatch, with a phase field) flow: without it the
    # only beats are this file's chunk-boundary ones and the supervisor
    # would record last_phase=null for every mid-chunk death.
    rec = None
    if hb is not None:
        from fps_tpu.obs import Recorder

        rec = Recorder(sinks=[child.HeartbeatSink(hb)])

    mesh = make_ps_mesh()
    W = num_workers_of(mesh)
    train, _ = logreg_data(args.examples)
    chunks = logreg_chunks(train, W, epochs=args.epochs)

    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg)
    if args.prefetch:
        import dataclasses

        trainer.config = dataclasses.replace(trainer.config,
                                             prefetch=args.prefetch)
    # One tier-enable implementation repo-wide (validation + the
    # push_delay-conflict check included).
    from fps_tpu.examples.common import apply_hot_tier

    apply_hot_tier(args, trainer, store)
    if args.retier_every:
        from fps_tpu.tiering import Retierer

        # Forced-cadence adaptive mode: re-rank on every check, tracker
        # state persisted beside the checkpoints so a supervised restart
        # replays the straight run's re-rank decisions bit-for-bit.
        trainer.retierer = Retierer(check_every=args.retier_every,
                                    churn_threshold=-1.0,
                                    state_dir=args.ckpt_dir)
    tables, ls = trainer.init_state(jax.random.key(0))

    ckpt_cls = Checkpointer if args.sync_checkpointer else AsyncCheckpointer
    ckpt = ckpt_cls(args.ckpt_dir, keep=3)
    start = ckpt.latest_valid_step() or 0
    tiering_restored = None
    if start:
        tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
        if trainer.retierer is not None:
            tiering_restored = trainer.retierer.restore(start)
    if hb is not None:
        # Beat-before-work: name the chunk about to be attempted BEFORE
        # attempting it, so a crash inside the very first (resumed) chunk
        # still attributes to it — without this, every resumed attempt
        # dies index-less and the supervisor can never quarantine a
        # deterministic mid-chunk poison (it would burn the whole retry
        # budget instead).
        hb.beat(index=start, attempt=attempt)
    meta = {"attempt": attempt, "restored_step": start,
            "quarantined": sorted(preset), "total_chunks": len(chunks)}
    print(json.dumps({"event": "demo_start", **meta}), flush=True)

    marker = os.path.join(args.ckpt_dir, "misbehave.done")
    wedge = None
    if args.wedge_at is not None:
        wedge = chaos.wedge_at_chunk(
            args.wedge_at, args.wedge_mode,
            marker=None if args.always else marker,
        )
    killer = None
    if args.kill_at is not None:
        # Flush first so the scenario's ≤1-chunk-lost bound holds under
        # the async writer (same reasoning as the wedge's flush below).
        killer = chaos.kill_at_chunk(
            args.kill_at,
            marker=None if args.always else os.path.join(
                args.ckpt_dir, "kill_at.done"),
            before=ckpt.flush,
        )

    def on_chunk(i, metrics):
        # The last beat before this point named chunk i (beat-before-work:
        # the post-restore beat, or the previous boundary's i-1 -> i).
        if (args.crash_at is not None and i == args.crash_at
                and i not in preset
                and (args.always or not os.path.exists(marker))):
            # A deterministic poison batch crashing the worker at chunk
            # i: dying BEFORE beating i+1 leaves i as the attempt's
            # last_index — the supervisor's quarantine evidence. No
            # marker touch — unlike the wedge, this MUST recur until
            # quarantined.
            print(json.dumps({"event": "demo_crash", "index": int(i)}),
                  flush=True)
            sys.stdout.flush()
            os._exit(3)
        if wedge is not None and i == args.wedge_at:
            # The scenario's exact ≤1-chunk-lost bound (restored_step ==
            # wedge_at) needs prior snapshots DURABLE before the freeze —
            # the async writer may still hold the latest save in flight,
            # and a SIGSTOP'd writer never finishes. The wedge models a
            # stall between chunks, so flushing first is faithful; a real
            # mid-write freeze is covered by victim-async-midwrite (the
            # bound there is the bit-identity contract, not a fixed step).
            ckpt.flush()
        if wedge is not None:
            wedge(i, metrics)
        if killer is not None:
            killer(i, metrics)
        if hb is not None:
            hb.beat(index=int(i) + 1, attempt=attempt)

    stream = chunks[start:]
    if (args.kill_prefetch_at is not None
            and args.kill_prefetch_at >= start):
        # Die while the background worker assembles this chunk (indices
        # in kill_in_prefetch are relative to the resumed stream).
        stream = chaos.kill_in_prefetch(
            iter(stream), args.kill_prefetch_at - start,
            marker=None if args.always else os.path.join(
                args.ckpt_dir, "prefetch_kill.done"),
        )

    rollback = RollbackPolicy(preset=preset) if preset else None
    tables, ls, _ = trainer.fit_stream(
        tables, ls, stream, jax.random.key(1),
        checkpointer=ckpt, checkpoint_every=1, start_step=start,
        on_chunk=on_chunk, rollback=rollback, recorder=rec,
    )
    ckpt.close()

    np.savez(args.out, weights=weights(store))
    meta.update(finished=True,
                skipped=sorted(rollback.skipped) if rollback else [],
                tiering_restored=tiering_restored,
                re_ranks=(trainer.retierer.re_ranks
                          if trainer.retierer is not None else None))
    with open(args.out + ".meta.json", "w", encoding="utf-8") as f:
        json.dump(meta, f)
    print(json.dumps({"event": "demo_done", **meta}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
