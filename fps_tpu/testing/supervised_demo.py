"""Tiny supervised training child: the real-jax end of the supervisor story.

``python -m fps_tpu.testing.supervised_demo --ckpt-dir D --out W.npz ...``
runs the standard tiny logreg workload (:mod:`fps_tpu.testing.workloads`)
under the full supervised-child contract:

* resumes from ``latest_valid_step`` in ``--ckpt-dir`` (fresh process,
  the framework's kill-resume contract) with ``checkpoint_every=1``
  through an :class:`~fps_tpu.core.checkpoint.AsyncCheckpointer`;
* beats the supervisor heartbeat (env contract,
  :mod:`fps_tpu.supervise.child`) on every chunk boundary;
* preloads the supervisor-carried quarantine set into
  ``RollbackPolicy(preset=...)``;
* misbehaves on demand — ``--wedge-at K`` (SIGSTOP / sleep-forever after
  chunk K trains, BEFORE its checkpoint lands: exactly one chunk of work
  at risk) or ``--crash-at K`` (deterministic exit(3): the poison-crash
  loop the supervisor must quarantine through). Both are once-only via a
  marker file next to the checkpoints unless ``--always`` is given.

Deterministic end to end: a supervised wedged run must reproduce the
straight run's final weights BIT-FOR-BIT (asserted by
``tools/chaos_sweep.py``'s ``supervised`` scenario and the slow test in
``tests/test_supervise.py``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Shared by the straight and supervised runs of the scenario below —
# bit-identity only means something when both children run the exact same
# workload.
SCENARIO_DEMO_ARGS = ("--examples", "8000", "--epochs", "2")
SCENARIO_WEDGE_AT = 3
# Mid-prefetch SIGKILL scenario: pipeline depth and the chunk whose
# background assembly the child dies in.
SCENARIO_PREFETCH_DEPTH = 2
SCENARIO_PREFETCH_KILL_AT = 4
# Hot-tier kill scenario: two-tier storage config and the chunk boundary
# the child dies at (between hot-tier reconciles from the snapshot
# trail's point of view: the chunk's own boundary reconcile ran, its
# checkpoint never landed).
SCENARIO_HOT_TIER = 64
SCENARIO_HOT_SYNC = 3
SCENARIO_HOT_KILL_AT = 3
# Retier-kill scenario: ADAPTIVE tier (mapped hot set + online
# tracking, forced re-rank every check) killed between a re-rank and
# the next checkpoint. check_every=2 puts re-rank checks at chunk
# boundaries 1, 3, 5...; the kill at chunk 3 fires BEFORE boundary 3's
# retier runs, so the restart must restore the last reconciled
# snapshot AND the step-3 tracker sidecar, re-plan (re-derive the hot
# set / replica / slot map), and replay chunk 3 bit-identically.
SCENARIO_RETIER_EVERY = 2
SCENARIO_RETIER_KILL_AT = 3
# Sharded-reconcile kill scenario (PR 10): a FULLY-replicated hot tier
# with a stateful Adagrad server fold — its per-row optimizer state is
# sharded over the replica axis by the reduce-scatter reconcile and
# persisted as fold:: checkpoint arrays. The SIGKILL lands between a
# reduce-scatter window (the chunk's boundary flush-reconcile ran, its
# Adagrad state advanced) and the next checkpoint; the restart must
# restore canonical tables AND the matching fold state, or the resumed
# Adagrad trajectory diverges from the straight run.
SCENARIO_FOLD_TIER = 400  # >= NF: full replication (hot_fold requires it)
SCENARIO_FOLD_SYNC = 3
SCENARIO_FOLD_KILL_AT = 3

# Megastep scenario (fps_tpu.core.megastep): K chunks per compiled
# dispatch over the device-ingest path; the kill lands after megastep
# SCENARIO_MEGASTEP_KILL_AT trains, before its boundary checkpoint.
MEGASTEP_T_CALL = 4          # steps per in-graph chunk segment
SCENARIO_MEGASTEP_K = 2
SCENARIO_MEGASTEP_KILL_AT = 3
# Delta-chain kill scenario (DeltaPolicy): a big feature table so each
# chunk touches a small fraction of rows (deltas actually engage — at
# tiny NF the size guard would publish fulls), a chain bound high enough
# that the whole run is one full + deltas, and a SIGKILL after chunk
# SCENARIO_DELTA_KILL_AT trains, before its delta lands — the restart
# must recover by walking the chain to its last verified link.
SCENARIO_DELTA_NF = 65536
SCENARIO_DELTA_BASE_ARGS = ("--examples", "8000", "--epochs", "2",
                            "--num-features", str(SCENARIO_DELTA_NF),
                            "--keep", "30")
SCENARIO_DELTA_ARGS = SCENARIO_DELTA_BASE_ARGS + (
    "--delta-full-every", "100")
SCENARIO_DELTA_KILL_AT = 3
# Fleet-fence scenario: N readers under quorum-2 fencing over the same
# delta-publishing child; one reader is killed+restarted mid-run.
SCENARIO_FLEET_READERS = 3


def _ttr_from_digest(digest) -> float | None:
    """Slowest ``restart_to_first_signal_s`` in a supervisor digest —
    the scenario's ``time_to_recovered_s`` figure the chaos sweep's
    time-to-recovered SLO gate judges (``None`` when the run never
    restarted: nothing recovered, nothing to bound)."""
    rts = digest.get("restart_to_first_signal_s") or []
    return round(max(rts), 3) if rts else None



def run_supervised_scenario(tmpdir: str, *, timeout: float = 600):
    """THE end-to-end supervisor survival scenario, shared by
    ``tools/chaos_sweep.py`` (``supervised``) and the slow test in
    ``tests/test_supervise.py`` so the two cannot drift: SIGSTOP-wedge a
    real training child mid-run; the supervisor must deadline-abort
    (SIGTERM→SIGKILL), restart with backoff, resume from
    ``latest_valid_step`` (exactly one chunk replayed), select no corrupt
    snapshot, and reproduce the unsupervised straight run's final weights
    bit-for-bit.

    Returns ``(ok, detail)`` — ``detail`` carries the evidence either
    caller surfaces (supervisor digest excerpt, restored step, the
    bit-identity verdict, any ``*.corrupt`` files).
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "10",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--wedge-at", str(SCENARIO_WEDGE_AT), "--wedge-mode", "sigstop"],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("deadline_aborts") == 1
          and digest.get("restarts") == 1
          # The wedge fires after chunk SCENARIO_WEDGE_AT trains (with
          # the async writer flushed first), before its checkpoint
          # lands: latest_valid_step == SCENARIO_WEDGE_AT means at most
          # one chunk of work was lost and replayed.
          and meta.get("restored_step") == SCENARIO_WEDGE_AT
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_prefetch_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL mid-PREFETCH under the supervisor: the child runs with the
    overlapped host pipeline on (``--prefetch 2``) and dies while the
    background worker is assembling chunk ``SCENARIO_PREFETCH_KILL_AT``
    (once, marker-gated) — a death BETWEEN chunk boundaries, several
    chunks ahead of the one being dispatched. The supervisor must see the
    crash, restart with backoff, and the resumed attempt (pipeline still
    on, resuming from ``latest_valid_step``) must finish clean and
    reproduce a straight pipeline-on run's final weights bit-for-bit.
    A single crash must NOT quarantine anything (quarantine needs two
    consecutive deaths at one index).

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS, "--prefetch", str(SCENARIO_PREFETCH_DEPTH)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-prefetch-at", str(SCENARIO_PREFETCH_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    # Sub-phase attribution evidence: the killed attempt's record must
    # carry the sub-chunk boundary the child last crossed. Which one is
    # timing-dependent (the worker dies while the driver is at its own
    # boundary), but it must be one of the driver's phases, not null.
    import json as _json

    try:
        with open(os.path.join(sup_dir, "supervisor_state.json"),
                  encoding="utf-8") as f:
            attempts = _json.load(f).get("attempts", [])
        killed_phase = attempts[0].get("last_phase") if attempts else None
    except (OSError, _json.JSONDecodeError, IndexError):
        killed_phase = None
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "bit_identical": bit_identical,
        "killed_attempt_phase": killed_phase,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          and killed_phase in ("prefetch", "ingest", "dispatch")
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_hot_tier_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between hot-tier reconciles under the supervisor: the
    child runs with two-tier storage on (``--hot-tier``/
    ``--hot-sync-every``, replicated head + per-device pending deltas)
    and dies at a chunk boundary before that chunk's checkpoint lands.
    The restart must restore from the last durable snapshot — by the
    flush-reconcile boundary invariant, always ONE canonical table with
    every hot push folded in — re-split the hot replica from it, and
    replay to final weights BIT-IDENTICAL to a straight (unkilled)
    tiered run. A single crash must not quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_HOT_TIER),
            "--hot-sync-every", str(SCENARIO_HOT_SYNC)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight tiered run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_HOT_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_HOT_KILL_AT trains (the
          # async writer flushed first) and before its checkpoint lands:
          # restored_step == SCENARIO_HOT_KILL_AT means exactly one chunk
          # was lost and replayed from a reconciled snapshot.
          and meta.get("restored_step") == SCENARIO_HOT_KILL_AT
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_megastep_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL mid-megastep under the supervisor: the child trains
    through the device-resident megastep driver (``--megastep K`` —
    K chunks fused per compiled dispatch, checkpoints at megastep
    boundaries) and dies after megastep ``SCENARIO_MEGASTEP_KILL_AT``
    trains, before its boundary checkpoint lands. The restart must
    restore the last window-boundary snapshot, resume at that megastep
    index (the per-(epoch, chunk) PRNG/shuffle derivation continues
    in-graph), and reproduce a straight megastep run's final weights
    BIT-identical. A single crash must not quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--megastep", str(SCENARIO_MEGASTEP_K)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight megastep run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_MEGASTEP_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after megastep SCENARIO_MEGASTEP_KILL_AT
          # trains (async writer flushed first), before its boundary
          # checkpoint lands: restored_step == the kill index means
          # exactly one megastep was lost and replayed.
          and meta.get("restored_step") == SCENARIO_MEGASTEP_KILL_AT
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_reconcile_shard_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between a sharded (reduce-scatter) reconcile window and
    the next checkpoint, with a stateful Adagrad hot-tier fold on
    (``--hot-fold adagrad``: per-row optimizer state sharded over the
    replica axis, persisted as ``fold::`` checkpoint arrays beside —
    never inside — the canonical table bytes). The restart must restore
    the last durable snapshot's canonical tables AND its fold state and
    replay to final weights BIT-IDENTICAL to a straight (unkilled) run —
    a fold state restarted from zeros would re-derive different Adagrad
    step sizes and diverge. A single crash must not quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_FOLD_TIER),
            "--hot-sync-every", str(SCENARIO_FOLD_SYNC),
            "--hot-fold", "adagrad"]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight hot-fold run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_FOLD_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    # The snapshots really carry the sharded fold state as its own kind:
    # canonical table bytes stay untouched (untiered readers skip
    # fold::), and a resume without it could not be bit-identical.
    fold_persisted = False
    snaps = sorted(glob.glob(os.path.join(sup_dir, "ckpt_*.npz")))
    if snaps:
        with np.load(snaps[-1]) as z:
            fold_persisted = any(k.startswith("fold::") for k in z.files)
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "fold_persisted": fold_persisted,
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_FOLD_KILL_AT trains (the
          # async writer flushed first) and before its checkpoint lands:
          # exactly one chunk lost, replayed from a snapshot holding
          # both the reconciled tables and the matching Adagrad state.
          and meta.get("restored_step") == SCENARIO_FOLD_KILL_AT
          and fold_persisted
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


def run_retier_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """SIGKILL between a hot-set re-rank and the next checkpoint, under
    the supervisor, with the ADAPTIVE tier on (``--hot-tier`` +
    ``--retier-every``: mapped hot set, device-side tracking, forced
    re-rank every check, tracker sidecars beside the checkpoints). The
    restart must restore the last reconciled snapshot (one canonical
    table — re-ranks never touch canonical rows), restore the matching
    tracker sidecar, re-derive the hot replica / slot map from both
    (``Trainer._attach_hot``), and replay to final weights BIT-IDENTICAL
    to a straight (unkilled) adaptive run — i.e. the resumed run's
    re-rank decisions are the straight run's. A single crash must not
    quarantine anything.

    Returns ``(ok, detail)`` like :func:`run_supervised_scenario`.
    """
    import numpy as np

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DEMO_ARGS,
            "--hot-tier", str(SCENARIO_HOT_TIER),
            "--hot-sync-every", str(SCENARIO_HOT_SYNC),
            "--retier-every", str(SCENARIO_RETIER_EVERY)]
    straight_dir = os.path.join(tmpdir, "straight")
    sup_dir = os.path.join(tmpdir, "sup")
    straight_out = os.path.join(tmpdir, "straight.npz")
    sup_out = os.path.join(tmpdir, "sup.npz")

    r = subprocess.run(
        demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        return False, {"error": "straight adaptive run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(straight_out + ".meta.json", encoding="utf-8") as f:
            straight_meta = json.load(f)
    except OSError:
        straight_meta = {}

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_RETIER_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=timeout,
    )
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (
        os.path.exists(sup_out)
        and np.array_equal(np.load(straight_out)["weights"],
                           np.load(sup_out)["weights"])
    )
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "restored_step": meta.get("restored_step"),
        "sidecar_restored": meta.get("tiering_restored"),
        "re_ranks": [straight_meta.get("re_ranks"), meta.get("re_ranks")],
        "bit_identical": bit_identical,
        "corrupt_files": sorted(os.path.basename(p) for p in
                                glob.glob(sup_dir + "/*.corrupt")),
    }
    ok = (r.returncode == 0 and digest.get("success")
          and digest.get("restarts") == 1
          # A SIGKILL crash is a death, not a stall: no deadline abort.
          and digest.get("deadline_aborts") == 0
          # One crash at one index is not quarantine evidence.
          and digest.get("quarantined") == []
          # The kill fires after chunk SCENARIO_RETIER_KILL_AT trains
          # (async writer flushed first), before its checkpoint lands.
          and meta.get("restored_step") == SCENARIO_RETIER_KILL_AT
          # The restart really restored the step-3 tracker sidecar —
          # without it the resumed re-rank decisions start cold and the
          # bit-identity below would be vacuous luck.
          and meta.get("tiering_restored") is True
          # The adaptive machinery actually exercised: the straight run
          # re-ranked at least once (forced-cadence mode re-ranks on the
          # first check; the resumed attempt's count may legitimately be
          # 0 on a stationary stream — its hot set is already ranked).
          and (straight_meta.get("re_ranks") or 0) >= 1
          and not detail["corrupt_files"]
          and bit_identical)
    return ok, detail


SCENARIO_SERVE_KILL_AT = 3


def run_serve_while_train_scenario(tmpdir: str, *, timeout: float = 600):
    """Serve-while-train survival (``fps_tpu.serve``, ``docs/serving.md``):
    a concurrent ReadServer polls a supervised child's checkpoint dir the
    whole run while the child is SIGKILLed after chunk
    ``SCENARIO_SERVE_KILL_AT`` trains (before its checkpoint lands) and a
    torn full-named snapshot candidate (a partial write that DID reach a
    published name) is planted mid-run. The read-path contract under test:

    * readers never observe a torn or CRC-failing table (the torn
      candidate is rejected, never served; every served pull returns
      finite rows from a verified snapshot);
    * the served step is monotone FORWARD for the whole (quarantine-free)
      run, kill and restart included, and ends on the newest valid
      snapshot with bytes equal to that snapshot's table;
    * when the final served snapshot is then quarantined (the trainer's
      ``*.corrupt`` rename), the reader swaps BACKWARD to the surviving
      snapshot — never keeps answering past the rollback.

    Returns ``(ok, detail)`` like the other scenarios; shared by
    ``tools/chaos_sweep.py`` (``serve_while_train``) and the slow test in
    ``tests/test_serve.py`` so the two cannot drift.
    """
    import subprocess as sp
    import time as _time

    import numpy as np

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.serve import ReadServer, SnapshotWatcher

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    sup_dir = os.path.join(tmpdir, "sup")
    sup_out = os.path.join(tmpdir, "sup.npz")
    proc = sp.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         sys.executable, "-m", "fps_tpu.testing.supervised_demo",
         *SCENARIO_DEMO_ARGS, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_SERVE_KILL_AT)],
        env=env, cwd=_ROOT, stdout=sp.PIPE, stderr=sp.PIPE, text=True,
    )

    server = ReadServer()
    swap_trail: list[tuple[str, int]] = []

    def on_swap(snap, direction):
        server.swap_to(snap)
        swap_trail.append((direction, snap.step))

    watcher = SnapshotWatcher(sup_dir, on_swap=on_swap)
    violations: list[str] = []
    served_steps: list[int] = []
    torn_planted = None
    deadline = _time.monotonic() + timeout
    while proc.poll() is None and _time.monotonic() < deadline:
        watcher.poll()
        snap = server._snap
        if snap is not None:
            step, rows = server.pull("weights", np.arange(
                snap.tables["weights"].shape[0]))
            if not np.all(np.isfinite(rows)):
                violations.append(f"non-finite rows served at step {step}")
            if served_steps and step < served_steps[-1]:
                violations.append(
                    f"served step went backward without a quarantine: "
                    f"{served_steps[-1]} -> {step}")
            served_steps.append(step)
            if torn_planted is None:
                # The partial-write injection that DID reach a published
                # name: a torn candidate NEWER than everything real. The
                # watcher must reject it and keep serving; the restarted
                # child's auto-resolve restore quarantines it.
                torn_planted = fmt.snapshot_path(sup_dir, snap.step + 50)
                with open(torn_planted, "wb") as f:
                    f.write(b"PK\x03\x04" + b"\xde\xad" * 512)
        _time.sleep(0.05)

    try:
        stdout, stderr = proc.communicate(timeout=max(
            5.0, deadline - _time.monotonic()))
    except sp.TimeoutExpired:
        proc.kill()
        return False, {"error": "supervised run timed out"}
    try:
        digest = json.loads(stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (stdout + stderr)[-1000:]}

    # Final convergence: the reader must end on the newest valid snapshot
    # with exactly its bytes.
    watcher.poll()
    final = fmt.latest_valid_snapshot(sup_dir)
    final_consistent = False
    if final is not None and server._snap is not None:
        want = fmt.map_snapshot_arrays(final[1])["table::weights"]
        _, got = server.pull("weights", np.arange(want.shape[0]))
        final_consistent = bool(server._snap.step == final[0]
                                and np.array_equal(got, want))

    # Rollback leg: quarantine the served snapshot the way the trainer
    # does (*.corrupt rename) — the reader must swap BACKWARD, not keep
    # answering from rolled-back-past state.
    backward_ok = False
    if server._snap is not None:
        quarantined_step = server._snap.step
        path = fmt.snapshot_path(sup_dir, quarantined_step)
        os.replace(path, path + ".corrupt")
        watcher.poll()
        snap = server._snap
        backward_ok = bool(snap is not None
                           and snap.step < quarantined_step
                           and swap_trail[-1][0] == "backward"
                           and np.all(np.isfinite(
                               server.pull("weights", [0, 1])[1])))

    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "attempts", "restarts",
                        "deadline_aborts", "quarantined")},
        "polls_served": len(served_steps),
        "served_step_span": ([served_steps[0], served_steps[-1]]
                             if served_steps else None),
        "swap_trail": swap_trail,
        "rejected_snapshots": watcher.rejected,
        "violations": violations,
        "final_consistent": final_consistent,
        "backward_swap_ok": backward_ok,
    }
    ok = bool(proc.returncode == 0 and digest.get("success")
              and digest.get("restarts") == 1
              and not violations
              and len(served_steps) > 0
              # The planted torn candidate was seen and refused.
              and watcher.rejected >= 1
              and final_consistent
              and backward_ok)
    return ok, detail


def _compaction_victim(ckpt_dir: str, phase: str) -> None:
    """Subprocess body for the delta-chain compaction kill: build a real
    delta chain, record its resolved state as ``expected.npz``, then
    SIGKILL OURSELVES at the named compaction phase (``precommit`` /
    ``published`` / ``swept_one`` — the Checkpointer's chaos seam). The
    parent verifies recovery with pure snapshot_format (no jax)."""
    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import (
        Checkpointer,
        DeltaPolicy,
        load_rows,
    )
    from fps_tpu.core.store import ParamStore, TableSpec
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.testing import chaos

    mesh = make_ps_mesh()
    store = ParamStore(mesh, [TableSpec("w", num_ids=1024, dim=8)])
    store.init(jax.random.key(0))
    ck = Checkpointer(ckpt_dir, keep=30, delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    rng = np.random.default_rng(7)
    for step in range(2, 6):
        ids = np.unique(rng.integers(0, 1024, 16))
        rows = store.lookup_host("w", ids)
        load_rows(store, "w", ids, rows + float(step))
        ck.save(step, store, None, touched_rows={"w": ids})
    np.savez(os.path.join(ckpt_dir, "expected.npz"),
             w=store.lookup_host("w", np.arange(1024)))
    if phase != "none":
        ck._compact_phase_hook = (
            lambda p: chaos.sigkill_self() if p == phase else None)
    ck.compact()


def run_delta_chain_kill_scenario(tmpdir: str, *, timeout: float = 600):
    """Delta-snapshot chains are crash-safe under injection
    (``docs/resilience.md``), in two legs:

    * **mid-chain publish kill** — a supervised child publishing one
      full + per-chunk deltas (``DeltaPolicy``) is SIGKILLed after chunk
      ``SCENARIO_DELTA_KILL_AT`` trains (async writer flushed, its delta
      not yet landed): the restart must walk the chain to its last
      verified link (``restored_step == kill_at``), replay exactly one
      chunk, and finish BIT-identical to a straight delta run — which
      itself must be bit-identical to a straight FULL-snapshot run (the
      delta encoding changes bytes-on-disk, never state);
    * **compaction kill, every phase** — a victim process folding a real
      chain is SIGKILLed at each compaction phase (post-fsync
      pre-rename / post-rename pre-sweep / mid-sweep): after every
      crash the directory must still resolve to the SAME state
      (``latest_valid_chain`` + ``resolve_chain_entries``, pure
      numpy), and a rerun compaction must complete and preserve it.
    """
    import subprocess as sp

    import numpy as np

    from fps_tpu.core import snapshot_format as fmt

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DELTA_ARGS]
    detail: dict = {}

    # Straight runs: full-snapshot baseline and delta chain.
    base_dir = os.path.join(tmpdir, "base")
    base_out = os.path.join(tmpdir, "base.npz")
    r = sp.run([sys.executable, "-m", "fps_tpu.testing.supervised_demo",
                *SCENARIO_DELTA_BASE_ARGS,
                "--ckpt-dir", base_dir, "--out", base_out],
               env=env, cwd=_ROOT, capture_output=True, text=True,
               timeout=timeout)
    if r.returncode != 0:
        return False, {"error": "straight full run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    straight_dir = os.path.join(tmpdir, "straight")
    straight_out = os.path.join(tmpdir, "straight.npz")
    r = sp.run(demo + ["--ckpt-dir", straight_dir, "--out", straight_out],
               env=env, cwd=_ROOT, capture_output=True, text=True,
               timeout=timeout)
    if r.returncode != 0:
        return False, {"error": "straight delta run failed",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    with open(straight_out + ".meta.json", encoding="utf-8") as f:
        straight_meta = json.load(f)
    detail["delta_publishes"] = straight_meta.get("delta_publishes")
    delta_vs_full = np.array_equal(np.load(base_out)["weights"],
                                   np.load(straight_out)["weights"])
    detail["delta_encoding_bit_identical"] = bool(delta_vs_full)

    # Supervised leg: SIGKILL mid-chain, supervisor restarts, resume
    # walks the chain.
    sup_dir = os.path.join(tmpdir, "sup")
    sup_out = os.path.join(tmpdir, "sup.npz")
    r = sp.run(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_DELTA_KILL_AT)],
        env=env, cwd=_ROOT, capture_output=True, text=True,
        timeout=timeout)
    try:
        digest = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (r.stdout + r.stderr)[-1000:]}
    try:
        with open(sup_out + ".meta.json", encoding="utf-8") as f:
            meta = json.load(f)
    except OSError:
        meta = {}
    bit_identical = (os.path.exists(sup_out)
                     and np.array_equal(np.load(straight_out)["weights"],
                                        np.load(sup_out)["weights"]))
    detail["supervised"] = {
        "restarts": digest.get("restarts"),
        "restored_step": meta.get("restored_step"),
        "delta_publishes": meta.get("delta_publishes"),
        "bit_identical": bit_identical,
    }
    sup_ok = bool(r.returncode == 0 and digest.get("success")
                  and digest.get("restarts") == 1
                  and meta.get("restored_step") == SCENARIO_DELTA_KILL_AT
                  and (straight_meta.get("delta_publishes") or 0) >= 2
                  and (meta.get("delta_publishes") or 0) >= 1
                  and delta_vs_full and bit_identical)

    # Compaction kill legs: every phase of the fold must leave a
    # recoverable, state-preserving directory.
    phases = {}
    for phase in ("precommit", "published", "swept_one"):
        d = os.path.join(tmpdir, f"compact_{phase}")
        victim = sp.run(
            [sys.executable, "-c",
             "from fps_tpu.testing.supervised_demo import "
             f"_compaction_victim; _compaction_victim({d!r}, {phase!r})"],
            env=env, cwd=_ROOT, capture_output=True, text=True,
            timeout=timeout)
        killed = victim.returncode == -9
        want = np.load(os.path.join(d, "expected.npz"))["w"]
        ok_state = False
        resolved = fmt.latest_valid_chain(d)
        if resolved is not None:
            entries = fmt.resolve_chain_entries(resolved[1])
            ok_state = (resolved[0] == 5
                        and np.array_equal(entries["table::w"], want))
        # Restartability: a rerun compaction (no kill) completes and
        # preserves the state.
        rerun = sp.run(
            [sys.executable, "-c",
             "from fps_tpu.core.checkpoint import Checkpointer, "
             "DeltaPolicy; Checkpointer("
             f"{d!r}, keep=30, delta=DeltaPolicy()).compact()"],
            env=env, cwd=_ROOT, capture_output=True, text=True,
            timeout=timeout)
        ok_rerun = False
        resolved2 = fmt.latest_valid_chain(d)
        if rerun.returncode == 0 and resolved2 is not None:
            entries2 = fmt.resolve_chain_entries(resolved2[1])
            ok_rerun = (resolved2[0] == 5
                        and resolved2[1][-1].kind == "full"
                        and np.array_equal(entries2["table::w"], want))
        phases[phase] = {"killed": killed, "recovered": ok_state,
                         "rerun_compacts": ok_rerun}
    detail["compaction"] = phases
    compact_ok = all(v["killed"] and v["recovered"] and
                     v["rerun_compacts"] for v in phases.values())
    return sup_ok and compact_ok, detail


def run_fleet_fence_scenario(tmpdir: str, *, timeout: float = 600):
    """Step-fenced serving fleet under churn (``docs/serving.md``):
    ``SCENARIO_FLEET_READERS`` fence-coordinated readers poll a
    supervised delta-publishing child's checkpoint dir while the child
    is SIGKILLed and restarted mid-run, and ONE reader is itself killed
    and restarted (a fresh FleetReader with the same id) mid-swap. The
    contract:

    * the shared fence is forward-monotone for the whole run (one
      fencing epoch — no quarantine here);
    * no reader ever serves a step older than the fence it observed at
      its own swap (per-reader served-step trails are monotone), and
      every answered pull returns finite rows;
    * the RESTARTED reader's first served step is >= the fence at its
      construction — a reader killed mid-swap never comes back
      answering a superseded step;
    * the fleet converges: every reader ends on the newest valid
      publication, byte-identical to the resolved chain.
    """
    import subprocess as sp
    import time as _time

    import numpy as np

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.serve import FleetReader, ServingFleet

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *SCENARIO_DELTA_ARGS]
    sup_dir = os.path.join(tmpdir, "sup")
    sup_out = os.path.join(tmpdir, "sup.npz")
    proc = sp.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
         "--state-dir", sup_dir, "--stall-timeout-s", "60",
         "--startup-grace-s", "300", "--term-grace-s", "2",
         "--backoff-base-s", "0.2", "--max-restarts", "2",
         "--poll-s", "0.2", "--",
         *demo, "--ckpt-dir", sup_dir, "--out", sup_out,
         "--kill-at", str(SCENARIO_DELTA_KILL_AT)],
        env=env, cwd=_ROOT, stdout=sp.PIPE, stderr=sp.PIPE, text=True)

    fleet = ServingFleet(sup_dir, SCENARIO_FLEET_READERS, quorum=2)
    violations: list[str] = []
    fence_trail: list[tuple[int, int]] = []
    restarted_first: list[tuple[int, int | None]] = []
    reader_killed = False
    deadline = _time.monotonic() + timeout
    polls = 0
    while proc.poll() is None and _time.monotonic() < deadline:
        fleet.poll()
        polls += 1
        fence = fleet.readers[0].fence.read()
        if fence is not None:
            if fence_trail and fence < fence_trail[-1]:
                violations.append(
                    f"fence went backward: {fence_trail[-1]} -> {fence}")
            if not fence_trail or fence != fence_trail[-1]:
                fence_trail.append(fence)
        for r in fleet.readers:
            snap = r.server._snap
            if snap is None:
                continue
            step, rows = r.server.pull("weights", np.arange(64))
            if not np.all(np.isfinite(rows)):
                violations.append(
                    f"{r.reader_id}: non-finite rows at step {step}")
        if (not reader_killed and fence_trail
                and fence_trail[-1][1] >= 2):
            # Kill reader r1 mid-run (drop it on the floor — a SIGKILL
            # from the reader's own point of view) and restart it as a
            # fresh process-equivalent: a brand-new FleetReader that
            # must re-read the fence BEFORE serving anything.
            reader_killed = True
            fence_at_boot = fleet.readers[1].fence.read()
            fleet.readers[1] = FleetReader(sup_dir, "r1", quorum=2)
            nr = fleet.readers[1]
            nr.poll()
            first = (None if nr.server._snap is None
                     else nr.server._snap.step)
            restarted_first.append((fence_at_boot[1], first))
            if first is not None and first < fence_at_boot[1]:
                violations.append(
                    f"restarted reader served {first} below the boot "
                    f"fence {fence_at_boot[1]}")
        _time.sleep(0.05)

    try:
        stdout, stderr = proc.communicate(timeout=max(
            5.0, deadline - _time.monotonic()))
    except sp.TimeoutExpired:
        proc.kill()
        return False, {"error": "supervised run timed out"}
    try:
        digest = json.loads(stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return False, {"error": "no supervisor digest",
                       "tail": (stdout + stderr)[-1000:]}

    # Convergence: every reader ends on the newest valid publication
    # with exactly the resolved chain's bytes.
    for _ in range(6):
        fleet.poll()
    final = fmt.latest_valid_chain(sup_dir)
    converged = False
    if final is not None:
        want = fmt.resolve_chain_entries(final[1])["table::weights"]
        converged = True
        for r in fleet.readers:
            snap = r.server._snap
            if snap is None or snap.step != final[0]:
                converged = False
                break
            _, got = r.server.pull("weights",
                                   np.arange(want.shape[0]))
            if not np.array_equal(got, want):
                converged = False
                break
    # Per-reader monotonicity of fence swaps (single epoch — no
    # quarantine in this scenario).
    monotone = all(all(b >= a for a, b in zip(r.served_steps,
                                              r.served_steps[1:]))
                   for r in fleet.readers)
    chain_served = max((r.server._snap.chain_len
                        for r in fleet.readers if r.server._snap
                        is not None), default=0)
    detail = {
        "time_to_recovered_s": _ttr_from_digest(digest),
        "supervisor": {k: digest.get(k) for k in
                       ("success", "restarts")},
        "polls": polls,
        "fence_trail": fence_trail[-8:],
        "restarted_reader": restarted_first,
        "served_monotone": monotone,
        "max_chain_len_served": chain_served,
        "violations": violations,
        "converged": converged,
    }
    ok = bool(proc.returncode == 0 and digest.get("success")
              and digest.get("restarts") == 1
              and reader_killed and not violations and monotone
              and len(fence_trail) >= 2
              # Delta chains actually served (incremental swaps ran).
              and chain_served >= 2
              and converged)
    return ok, detail


# ---------------------------------------------------------------------------
# Pod-level scenarios (fps_tpu.supervise.pod): N member agents over one
# shared pod dir, each supervising its own replica of the demo child —
# the single-machine stand-in for N symmetric hosts of one SPMD job.
# ---------------------------------------------------------------------------

SCENARIO_POD_HOSTS = ("h0", "h1", "h2")
SCENARIO_POD_KILL_AT = 3
SCENARIO_POD_CRASH_AT = 5
# The partition scenario needs the children still RUNNING when the
# post-seizure fence lands (a few seconds after the leader freezes), so
# the stale leader's orphan demonstrably hits the fence: pace every
# chunk boundary with a deterministic sleep.
SCENARIO_PARTITION_ARGS = ("--examples", "8000", "--epochs", "6",
                           "--chunk-sleep-s", "0.3")
SCENARIO_ELASTIC_ARGS = ("--examples", "20000", "--epochs", "4")


def _pod_child_env():
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=_ROOT)
    return env


def _load_trace_export():
    """tools/trace_export.py by file path (tools/ is not a package)."""
    import importlib.util

    path = os.path.join(_ROOT, "tools", "trace_export.py")
    spec = importlib.util.spec_from_file_location("_fps_trace_export",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _export_pod_trace(pod_dir: str, hosts):
    """The pod chaos scenarios' trace evidence: export the pod dir's
    merged Chrome/Perfetto trace (written next to the journals) and
    summarize the coordinated-restart span trees — one entry per
    ``pod_restart`` decision, with the per-host attempt children and the
    fencing epoch each child carries."""
    te = _load_trace_export()
    spans = te.collect_spans([pod_dir])
    doc = te.export_chrome(spans)
    out_path = os.path.join(pod_dir, "pod_trace.json")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    trees = te.coordinated_restart_trees(spans)
    summary = []
    for tree in trees:
        children = tree["children"]
        attempts = [c for c in children if c["cat"] == "attempt"]
        epoch = tree["epoch"]
        summary.append({
            "epoch": epoch,
            "children": len(children),
            "attempt_hosts": sorted({c.get("host") for c in attempts}),
            # The fencing epoch must ride EVERY child span: attempts
            # carry it as pod_epoch, the fence write as min_epoch.
            "children_carry_epoch": all(
                (c["attrs"].get("pod_epoch")
                 if c["cat"] == "attempt"
                 else c["attrs"].get("min_epoch")) == epoch
                for c in children) if children else False,
        })
    return {
        "trace_path": out_path,
        "trace_events": len(doc["traceEvents"]),
        "spans": len(spans),
        "restart_trees": summary,
    }


def _pod_fleet_digest(pod_dir: str, hosts):
    """Fleet rollup + SLO burn over the member dirs (each holds the
    child's --obs-dir telemetry beside its snapshots) — attached to the
    chaos digest so the sweep carries the fleet-level evidence."""
    from fps_tpu.obs import fleet

    # The pod dir itself rides along: journal-pod.jsonl holds the
    # pod_restart events the rollup's restart counter folds.
    digest = fleet.fleet_digest(
        [pod_dir] + [os.path.join(pod_dir, h) for h in hosts])
    roll = digest["rollup"]
    return {
        "hosts": roll["hosts"],
        "window_s": roll["window_s"],
        "windows": len(roll["windows"]),
        "totals": roll["totals"],
        "slo": digest["slo"],
    }


def _launch_pod(pod_dir: str, child_args, *, hosts=SCENARIO_POD_HOSTS,
                pod_flags=(), member_flags=()):
    """Start one pod-member process per host (each supervising its own
    demo child); returns {host: Popen}."""
    os.makedirs(pod_dir, exist_ok=True)
    env = _pod_child_env()
    procs = {}
    for h in hosts:
        cmd = [
            sys.executable, os.path.join(_ROOT, "tools", "supervise.py"),
            "--pod-dir", pod_dir, "--pod-host", h,
            "--pod-size", str(len(hosts)), *pod_flags,
            "--stall-timeout-s", "20", "--startup-grace-s", "300",
            "--term-grace-s", "2", "--backoff-base-s", "0.2",
            "--backoff-max-s", "2", "--max-restarts", "6",
            "--poll-s", "0.15", "--lease-ttl-s", "1.5",
            "--member-timeout-s", "4", *member_flags, "--",
            sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            *child_args, "--keep", "20",
            "--ckpt-dir", os.path.join(pod_dir, "{host}"),
            "--obs-dir", os.path.join(pod_dir, "{host}"),
            "--out", os.path.join(pod_dir, "{host}", "out.npz"),
        ]
        procs[h] = subprocess.Popen(
            cmd, env=env, cwd=_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
    return procs


def _collect_pod(procs: dict, timeout: float) -> dict:
    """Wait for every member; returns {host: {"rc", "digest", "tail"}}."""
    import time as _time

    out = {}
    deadline = _time.monotonic() + timeout
    for h, p in procs.items():
        try:
            stdout, _ = p.communicate(
                timeout=max(5.0, deadline - _time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            stdout, _ = p.communicate()
        digest = None
        try:
            digest = json.loads(stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            pass
        out[h] = {"rc": p.returncode, "digest": digest,
                  "tail": stdout[-1200:]}
    return out


def _run_straight(tmpdir: str, child_args, *, timeout: float,
                  preset_quarantine=None):
    """One unsupervised demo run → (ok, weights_path, tail). With
    ``preset_quarantine``, the run carries that quarantine set through
    the supervised-child env contract (the straight twin of a pod run
    that quarantined those chunks)."""
    env = _pod_child_env()
    if preset_quarantine:
        state = os.path.join(tmpdir, "straight_state.json")
        with open(state, "w", encoding="utf-8") as f:
            json.dump({"quarantined": sorted(preset_quarantine)}, f)
        env["FPS_TPU_SUPERVISOR_STATE"] = state
    straight_dir = os.path.join(tmpdir, "straight")
    straight_out = os.path.join(tmpdir, "straight.npz")
    r = subprocess.run(
        [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
         *child_args, "--ckpt-dir", straight_dir, "--out", straight_out],
        env=env, cwd=_ROOT, capture_output=True, text=True,
        timeout=timeout)
    return r.returncode == 0, straight_out, (r.stdout + r.stderr)[-1000:]


def _pod_dirs_clean(pod_dir: str, hosts) -> list[str]:
    """Corrupt-quarantined files and TORN PUBLISHED snapshots across all
    member dirs — must be empty. (``*.tmp.npz`` leftovers of a child
    SIGKILLed mid-write are NOT debris here: they were never published,
    and the checkpointer's construction sweep collects them — the
    acceptance bar is zero torn checkpoints *published*.)"""
    import zipfile

    bad = [p for h in hosts
           for p in glob.glob(os.path.join(pod_dir, h, "*.corrupt"))]
    for h in hosts:
        for p in glob.glob(os.path.join(pod_dir, h, "ckpt_*.npz")):
            try:
                with zipfile.ZipFile(p) as z:
                    if z.testzip() is None:
                        continue
            except (OSError, zipfile.BadZipFile):
                pass
            bad.append(p + ":torn")
    return sorted(os.path.relpath(p, pod_dir) for p in bad)


def _stale_publishes(pod_dir: str, hosts) -> list[str]:
    """Snapshots written AFTER their dir's fence yet stamped with an
    epoch below it — the publishes the fence exists to prevent. Must be
    empty in every pod scenario."""
    import numpy as np

    bad = []
    for h in hosts:
        d = os.path.join(pod_dir, h)
        fence_path = os.path.join(d, "pod_fence.json")
        try:
            with open(fence_path, encoding="utf-8") as f:
                fence = json.load(f)
            fence_mtime = os.stat(fence_path).st_mtime_ns
            min_epoch = int(fence["min_epoch"])
        except (OSError, KeyError, ValueError, json.JSONDecodeError):
            continue
        for p in sorted(glob.glob(os.path.join(d, "ckpt_*.npz"))):
            try:
                if os.stat(p).st_mtime_ns <= fence_mtime:
                    continue
                with np.load(p) as z:
                    epoch = (int(z["meta::pod_epoch"])
                             if "meta::pod_epoch" in z.files else None)
            except (OSError, ValueError):
                continue
            if epoch is not None and epoch < min_epoch:
                bad.append(f"{h}/{os.path.basename(p)}:epoch{epoch}"
                           f"<fence{min_epoch}")
    return bad


def _pod_bit_identity(pod_dir: str, hosts, straight_out: str):
    """(all_identical, per-host detail) of member outputs vs straight."""
    import numpy as np

    want = np.load(straight_out)["weights"]
    detail = {}
    for h in hosts:
        p = os.path.join(pod_dir, h, "out.npz")
        detail[h] = bool(os.path.exists(p)
                         and np.array_equal(np.load(p)["weights"], want))
    return all(detail.values()), detail


def run_pod_kill_one_host_scenario(tmpdir: str, *, timeout: float = 600):
    """ONE member's child is SIGKILLed mid-run: the leader must make ONE
    pod-wide decision — coordinated abort of every member's child,
    restart of all three from the COMMON ``latest_valid_step`` — after
    which every member finishes bit-identical to an uninterrupted run.
    One crash quarantines nothing and evicts nobody.
    """
    ok, straight_out, tail = _run_straight(
        tmpdir, SCENARIO_DEMO_ARGS, timeout=timeout)
    if not ok:
        return False, {"error": "straight run failed", "tail": tail}
    pod_dir = os.path.join(tmpdir, "pod")
    procs = _launch_pod(
        pod_dir,
        (*SCENARIO_DEMO_ARGS, "--kill-at", str(SCENARIO_POD_KILL_AT),
         "--misbehave-host", "h1"))
    res = _collect_pod(procs, timeout)
    digests = {h: r["digest"] for h, r in res.items()}
    if any(r["digest"] is None for r in res.values()):
        return False, {"error": "missing member digest",
                       "tails": {h: r["tail"] for h, r in res.items()}}
    bit_identical, bit_detail = _pod_bit_identity(
        pod_dir, SCENARIO_POD_HOSTS, straight_out)
    trace = _export_pod_trace(pod_dir, SCENARIO_POD_HOSTS)
    trees = trace["restart_trees"]
    # THE tracing acceptance: the coordinated restart exports as ONE
    # span tree — a single pod_restart parent whose per-host attempt
    # children all carry the fencing epoch — not N disconnected
    # per-host journal fragments.
    trace_ok = (len(trees) == 1
                and trees[0]["attempt_hosts"]
                == sorted(SCENARIO_POD_HOSTS)
                and trees[0]["children_carry_epoch"]
                and trace["trace_events"] > 0)
    detail = {
        "digests": {h: {k: d[k] for k in
                        ("success", "attempts", "epoch", "pod")}
                    for h, d in digests.items()},
        "bit_identical": bit_detail,
        "debris": _pod_dirs_clean(pod_dir, SCENARIO_POD_HOSTS),
        "stale_publishes": _stale_publishes(pod_dir, SCENARIO_POD_HOSTS),
        "kill_fired": os.path.exists(
            os.path.join(pod_dir, "h1", "kill_at.done")),
        "trace": trace,
        "fleet": _pod_fleet_digest(pod_dir, SCENARIO_POD_HOSTS),
    }
    ok = (all(r["rc"] == 0 and r["digest"]["success"]
              for r in res.values())
          # ONE pod-wide decision, not per-host timers: exactly one
          # coordinated restart, shared by every member's digest.
          and all(d["pod"]["restarts"] == 1 for d in digests.values())
          and all(d["pod"]["quarantined"] == [] for d in digests.values())
          and all(d["pod"]["evicted"] == [] for d in digests.values())
          and detail["kill_fired"]
          and trace_ok
          and not detail["debris"] and not detail["stale_publishes"]
          and bit_identical)
    return ok, detail


def run_pod_partition_coordinator_scenario(tmpdir: str, *,
                                           timeout: float = 600):
    """The LEASE HOLDER's member agent is SIGSTOPped mid-run (a
    partitioned coordinator host: its child keeps training, orphaned). A
    follower must seize the expired lease (fencing epoch bump), treat the
    unreachable member as failed, fence every member dir, and command a
    coordinated restart — and the stale leader's orphan child must be
    REFUSED by the fence when it next publishes. On SIGCONT the deposed
    leader rejoins as a follower and the pod completes bit-identical to
    an uninterrupted run.
    """
    import time as _time

    ok, straight_out, tail = _run_straight(
        tmpdir, SCENARIO_PARTITION_ARGS, timeout=timeout)
    if not ok:
        return False, {"error": "straight run failed", "tail": tail}
    pod_dir = os.path.join(tmpdir, "pod")
    # Tighter unreachable-member detection: the fence must land while
    # the frozen leader's orphan is still mid-run (argparse keeps the
    # LAST occurrence, so this overrides the launch default).
    procs = _launch_pod(pod_dir, SCENARIO_PARTITION_ARGS,
                        member_flags=("--member-timeout-s", "3"))

    lease_path = os.path.join(pod_dir, "pod_lease.json")

    def _read_json(path):
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    deadline = _time.monotonic() + timeout
    leader = seized_by = None
    import signal as _signal

    stopped_pid = None
    try:
        # Wait for a leader AND its first published snapshot (the run is
        # really underway), then freeze the leader's member agent.
        while _time.monotonic() < deadline:
            lease = _read_json(lease_path)
            if lease and lease.get("host"):
                mem = _read_json(os.path.join(
                    pod_dir, "members", lease["host"] + ".json"))
                if mem and (mem.get("latest_step") or 0) >= 1:
                    leader = lease["host"]
                    stopped_pid = procs[leader].pid
                    os.kill(stopped_pid, _signal.SIGSTOP)
                    break
            _time.sleep(0.1)
        if leader is None:
            return False, {"error": "no leader emerged"}
        # Wait for the seizure (lease holder changes, epoch grows).
        while _time.monotonic() < deadline:
            lease = _read_json(lease_path)
            if lease and lease.get("host") not in (None, leader):
                seized_by = lease["host"]
                break
            _time.sleep(0.1)
        # Give the new leader time to fence + restart and the orphan
        # time to run into the fence, then release the old leader.
        _time.sleep(6.0)
    finally:
        if stopped_pid is not None:
            try:
                os.kill(stopped_pid, _signal.SIGCONT)
            except ProcessLookupError:
                pass

    res = _collect_pod(procs, max(10.0, deadline - _time.monotonic()))
    digests = {h: r["digest"] for h, r in res.items()}
    if any(r["digest"] is None for r in res.values()):
        return False, {"error": "missing member digest", "leader": leader,
                       "tails": {h: r["tail"] for h, r in res.items()}}
    # The orphan's epitaph: its attempt log must show the fence refusal
    # (StaleEpochError) — the "stale leader cannot publish" half of the
    # acceptance criterion; the mtime/epoch scan is the on-disk half.
    fenced_logs = []
    for p in glob.glob(os.path.join(pod_dir, leader, "attempt-*.log")):
        try:
            with open(p, encoding="utf-8", errors="replace") as f:
                if "StaleEpochError" in f.read():
                    fenced_logs.append(os.path.basename(p))
        except OSError:
            pass
    bit_identical, bit_detail = _pod_bit_identity(
        pod_dir, SCENARIO_POD_HOSTS, straight_out)
    trace = _export_pod_trace(pod_dir, SCENARIO_POD_HOSTS)
    trees = trace["restart_trees"]
    # Tracing acceptance under partition: the pod's FINAL coordinated
    # restart (the new leader's post-seizure decision) exports as
    # exactly ONE span tree — one parent span at the final run epoch
    # with attempt children from every host, each carrying the fencing
    # epoch. (A paced unreachable-member incident may legitimately spend
    # a second restart while the old leader is frozen; each is its own
    # well-formed tree, and the final one must have gathered the whole
    # pod.)
    final = [t for t in trees if trees and t["epoch"]
             == max(x["epoch"] for x in trees)]
    trace_ok = (len(trees) >= 1 and len(final) == 1
                and final[0]["attempt_hosts"]
                == sorted(SCENARIO_POD_HOSTS)
                and all(t["children_carry_epoch"] for t in trees
                        if t["children"])
                and trace["trace_events"] > 0)
    detail = {
        "stopped_leader": leader,
        "seized_by": seized_by,
        "fenced_logs": sorted(fenced_logs),
        "digests": {h: {k: d[k] for k in
                        ("success", "leader_terms", "epoch", "pod")}
                    for h, d in digests.items()},
        "bit_identical": bit_detail,
        "debris": _pod_dirs_clean(pod_dir, SCENARIO_POD_HOSTS),
        "stale_publishes": _stale_publishes(pod_dir, SCENARIO_POD_HOSTS),
        "trace": trace,
    }
    ok = (all(r["rc"] == 0 and r["digest"]["success"]
              for r in res.values())
          and seized_by is not None and seized_by != leader
          and digests[seized_by]["leader_terms"] >= 1
          and bool(fenced_logs)
          and trace_ok
          and all(d["pod"]["quarantined"] == [] for d in digests.values())
          and not detail["debris"] and not detail["stale_publishes"]
          and bit_identical)
    return ok, detail


def run_pod_flapping_member_scenario(tmpdir: str, *, timeout: float = 600):
    """One member's child crashes deterministically at the same chunk on
    every attempt (a flapping member). The pod must converge in two
    coordinated restarts: crash, crash → the leader quarantines that
    chunk POD-WIDE, and the third attempt completes with EVERY member
    skipping it — no host re-dispatches a chunk another host proved
    poisonous. Bit-identity target: a straight run carrying the same
    quarantine preset.
    """
    ok, straight_out, tail = _run_straight(
        tmpdir, SCENARIO_DEMO_ARGS, timeout=timeout,
        preset_quarantine={SCENARIO_POD_CRASH_AT})
    if not ok:
        return False, {"error": "straight run failed", "tail": tail}
    pod_dir = os.path.join(tmpdir, "pod")
    procs = _launch_pod(
        pod_dir,
        (*SCENARIO_DEMO_ARGS, "--crash-at", str(SCENARIO_POD_CRASH_AT),
         "--misbehave-host", "h1"))
    res = _collect_pod(procs, timeout)
    digests = {h: r["digest"] for h, r in res.items()}
    if any(r["digest"] is None for r in res.values()):
        return False, {"error": "missing member digest",
                       "tails": {h: r["tail"] for h, r in res.items()}}
    # The broadcast, observed at the CHILDREN: every member's final meta
    # shows the quarantined chunk skipped — including the members whose
    # own children never crashed.
    skipped = {}
    for h in SCENARIO_POD_HOSTS:
        try:
            with open(os.path.join(pod_dir, h, "out.npz.meta.json"),
                      encoding="utf-8") as f:
                skipped[h] = json.load(f).get("skipped")
        except OSError:
            skipped[h] = None
    bit_identical, bit_detail = _pod_bit_identity(
        pod_dir, SCENARIO_POD_HOSTS, straight_out)
    detail = {
        "digests": {h: {k: d[k] for k in ("success", "attempts", "pod")}
                    for h, d in digests.items()},
        "skipped": skipped,
        "bit_identical": bit_detail,
        "debris": _pod_dirs_clean(pod_dir, SCENARIO_POD_HOSTS),
        "stale_publishes": _stale_publishes(pod_dir, SCENARIO_POD_HOSTS),
    }
    ok = (all(r["rc"] == 0 and r["digest"]["success"]
              for r in res.values())
          and all(d["pod"]["quarantined"] == [SCENARIO_POD_CRASH_AT]
                  for d in digests.values())
          and all(d["pod"]["restarts"] == 2 for d in digests.values())
          and all(d["pod"]["evicted"] == [] for d in digests.values())
          and all(skipped[h] == [SCENARIO_POD_CRASH_AT]
                  for h in SCENARIO_POD_HOSTS)
          and not detail["debris"] and not detail["stale_publishes"]
          and bit_identical)
    return ok, detail


def run_pod_elastic_resize_scenario(tmpdir: str, *, timeout: float = 600):
    """The elastic W→W−1→W path: a whole HOST dies (member agent AND its
    child SIGKILLed) after the pod has made progress. The leader, past
    that member's budget (``--evict-after 1``: one disappearance of a
    dead host), re-plans the run at W−1 and the survivors continue. The
    host then RETURNS (its member agent relaunched); the leader syncs it
    the newest canonical snapshot (the elastic re-split source) and
    restarts the pod at W. Every member — the returned one included —
    must finish byte-identical to a straight W-host run at the same step
    count, with zero torn or epoch-stale checkpoints published.
    """
    import signal as _signal
    import time as _time

    ok, straight_out, tail = _run_straight(
        tmpdir, SCENARIO_ELASTIC_ARGS, timeout=timeout)
    if not ok:
        return False, {"error": "straight run failed", "tail": tail}
    pod_dir = os.path.join(tmpdir, "pod")
    pod_flags = ("--elastic", "--evict-after", "1",
                 "--rejoin-delay-s", "0.3")
    procs = _launch_pod(pod_dir, SCENARIO_ELASTIC_ARGS,
                        pod_flags=pod_flags)

    def _read_json(path):
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    deadline = _time.monotonic() + timeout
    victim = None
    # Kill a NON-leader host once the pod has real progress (the victim
    # has published snapshots the post-return catch-up can be measured
    # against).
    while _time.monotonic() < deadline:
        lease = _read_json(os.path.join(pod_dir, "pod_lease.json"))
        leader = (lease or {}).get("host")
        if leader in SCENARIO_POD_HOSTS:
            for h in SCENARIO_POD_HOSTS:
                if h == leader:
                    continue
                mem = _read_json(os.path.join(pod_dir, "members",
                                              h + ".json"))
                if mem and (mem.get("latest_step") or 0) >= 2:
                    victim = h
                    for pid in (mem.get("child_pid"), procs[h].pid):
                        if pid:
                            try:
                                os.killpg(pid, _signal.SIGKILL)
                            except (OSError, ProcessLookupError):
                                try:
                                    os.kill(pid, _signal.SIGKILL)
                                except (OSError, ProcessLookupError):
                                    pass
                    break
            if victim is not None:
                break
        _time.sleep(0.1)
    if victim is None:
        for p in procs.values():
            p.kill()
        return False, {"error": "no victim reached step 2 in time"}

    # Wait for the eviction (control world drops to 2), then RETURN the
    # host: relaunch its member agent with the identical command.
    saw_evicted_world = None
    while _time.monotonic() < deadline:
        ctl = _read_json(os.path.join(pod_dir, "pod_control.json"))
        if ctl and ctl.get("action") == "run" and ctl.get("world") == 2:
            saw_evicted_world = 2
            break
        if ctl and ctl.get("action") in ("shutdown", "give_up"):
            break
        _time.sleep(0.1)
    if saw_evicted_world == 2:
        procs[victim].wait()  # reap the killed agent
        relaunched = _launch_pod(pod_dir, SCENARIO_ELASTIC_ARGS,
                                 hosts=(victim,), pod_flags=pod_flags)
        procs[victim] = relaunched[victim]

    res = _collect_pod(procs, max(10.0, deadline - _time.monotonic()))
    digests = {h: r["digest"] for h, r in res.items()}
    if any(r["digest"] is None for h, r in res.items() if h != victim) \
            or res[victim]["digest"] is None:
        return False, {"error": "missing member digest",
                       "victim": victim,
                       "tails": {h: r["tail"] for h, r in res.items()}}
    victim_meta = _read_json(os.path.join(
        pod_dir, victim, "out.npz.meta.json")) or {}
    bit_identical, bit_detail = _pod_bit_identity(
        pod_dir, SCENARIO_POD_HOSTS, straight_out)
    detail = {
        "victim": victim,
        "evicted_world_observed": saw_evicted_world,
        "digests": {h: {k: d[k] for k in ("success", "attempts", "pod")}
                    for h, d in digests.items()},
        "victim_restored_step": victim_meta.get("restored_step"),
        "bit_identical": bit_detail,
        "debris": _pod_dirs_clean(pod_dir, SCENARIO_POD_HOSTS),
        "stale_publishes": _stale_publishes(pod_dir, SCENARIO_POD_HOSTS),
    }
    ok = (all(r["rc"] == 0 and r["digest"]["success"]
              for r in res.values())
          and saw_evicted_world == 2
          # The full elastic cycle: one eviction (W→W−1), one
          # readmission (W−1→W), ending with all three back in the plan.
          and all(d["pod"]["readmissions"] == 1 for d in digests.values())
          and all(d["pod"]["world"] == 3 for d in digests.values())
          and all(d["pod"]["evicted"] == [] for d in digests.values())
          and all(d["pod"]["quarantined"] == [] for d in digests.values())
          # The returned host resumed from the SYNCED canonical
          # snapshot — caught up, not cold-started.
          and (victim_meta.get("restored_step") or 0) >= 2
          and not detail["debris"] and not detail["stale_publishes"]
          and bit_identical)
    return ok, detail


# ---------------------------------------------------------------------------
# Hostile-filesystem scenarios (fps_tpu.testing.faultfs + fps_tpu/core/
# retry.py): deterministic, seed-replayable I/O fault injection against
# the framework's own storage seams. In-process by design — the injector
# is process-global and the faults are in the FILESYSTEM, not the
# process tree; docs/resilience.md "Hostile filesystem" is the failure-
# model table these scenarios pin.
# ---------------------------------------------------------------------------


def _storage_harness():
    """Tiny logreg harness shared by the storage scenarios: returns
    ``(mesh, chunks, make_trainer)`` — same workload both arms, so
    bit-identity is meaningful. Sized for exactly 12 chunks (6 per
    epoch) at ANY mesh width, so the deterministic per-operation fault
    schedules land on the same publishes everywhere."""
    import numpy as np

    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.testing.workloads import NF, NNZ

    mesh = make_ps_mesh()
    W = num_workers_of(mesh)
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    n = W * 32 * 8 * 6  # exactly 6 chunks/epoch at any mesh width
    data = synthetic_sparse_classification(n, NF, NNZ, seed=7,
                                           noise=0.05)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))
    chunks = list(multi_epoch_chunks(data, 2, num_workers=W,
                                     local_batch=32, steps_per_chunk=8,
                                     seed=3))

    def make_trainer():
        cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
        trainer, store = logistic_regression(mesh, cfg)
        tables, ls = trainer.init_state(jax.random.key(0))
        return trainer, store, tables, ls

    return mesh, chunks, make_trainer


def run_storage_brownout_scenario(tmpdir: str, *, timeout: float = 600):
    """Storage BROWNOUT under live training + a serving fleet: a mixed
    deterministic fault schedule (transient EIO writes, slow fsyncs,
    EIO/stale/ENOENT reads, one torn rename, flaky directory scans)
    hits the snapshot plane mid-run, then recovers. The contract:

    * training never crashes; final weights are BIT-identical to the
      fault-free run (storage faults cost recency, never state);
    * at least one publish DEGRADES (skipped, backlog raised) and the
      backlog drains to 0 after recovery, with the final snapshot's
      state bit-identical to the clean run's;
    * the 2-reader quorum fleet serves last-good throughout — fence
      forward-monotone (single epoch), no reader ever serves a step
      ahead of the fence or an unverified/torn candidate — and
      converges on the newest valid publication after recovery;
    * the read plane's degradation is VISIBLE (poll_errors > 0), never
      a frozen reader.
    """
    import numpy as np

    import jax

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.serve import ServingFleet
    from fps_tpu.testing import faultfs
    from fps_tpu.testing.faultfs import FaultRule
    from fps_tpu.testing.workloads import weights

    _mesh, chunks, make_trainer = _storage_harness()

    # Clean arm (no injector): the bit-identity reference.
    trainer, store, tables, ls = make_trainer()
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))
    want_w = weights(store).copy()

    n_chunks = len(chunks)
    rules = [
        # One publish's whole retry budget fails (degrade), the next
        # fails twice then lands (retried-then-successful).
        FaultRule("snapshot", "write", "errno", errno_name="EIO",
                  start=2, count=6),
        # A torn rename mid-run: a truncated file lands at the
        # destination and the CRC gates must reject it until the retry
        # overwrites it.
        FaultRule("snapshot", "replace", "torn", start=8, count=1),
        # Brownout latency on every 4th fsync.
        FaultRule("snapshot", "fsync", "delay", delay_s=0.01,
                  start=0, count=None, every=4),
        # Read-plane hostility: transient EIO, stale read-after-rename,
        # and flaky directory scans against the fleet's watcher.
        FaultRule("snapshot", "read", "errno", errno_name="EIO",
                  start=4, count=3),
        FaultRule("snapshot", "read", "stale", start=12, count=2),
        FaultRule("snapshot", "listdir", "errno", errno_name="EIO",
                  start=6, count=3),
    ]
    d = os.path.join(tmpdir, "brownout")
    trainer, store, tables, ls = make_trainer()
    fs = faultfs.install(rules, seed=0)
    violations: list[str] = []
    fence_trail: list[tuple[int, int]] = []
    try:
        ck = AsyncCheckpointer(d, keep=n_chunks + 2)
        fleet = ServingFleet(d, 2, quorum=2)

        def on_chunk(step, _metrics):
            fleet.poll()
            fence = fleet.readers[0].fence.read()
            if fence is not None:
                if fence_trail and fence < fence_trail[-1]:
                    violations.append(
                        f"fence went backward: {fence_trail[-1]} -> "
                        f"{fence}")
                if not fence_trail or fence != fence_trail[-1]:
                    fence_trail.append(fence)
            for r in fleet.readers:
                snap = r.server._snap
                if snap is not None and fence is not None \
                        and snap.step > fence[1]:
                    violations.append(
                        f"{r.reader_id} served {snap.step} ahead of "
                        f"fence {fence[1]}")

        tables, ls, _ = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1),
            checkpointer=ck, checkpoint_every=1, on_chunk=on_chunk)
        ck.flush()
        degraded = ck.degraded_publishes
        backlog = ck._publish_backlog
        # Recovery convergence: rules are exhausted by now (bounded
        # counts); the fleet must converge on the newest valid step.
        fs.quiesce()
        for _ in range(12):
            fleet.poll()
        final_step = ck.latest_valid_step()
        _, snap_tables, _, _ = ck.read_snapshot(final_step)
        converged = all(
            r.server._snap is not None
            and r.server._snap.step == final_step
            for r in fleet.readers)
        poll_errors = (sum(r.poll_errors for r in fleet.readers)
                       + sum(r.watcher.poll_errors
                             for r in fleet.readers))
        served_monotone = all(
            all(b >= a for a, b in zip(r.served_steps,
                                       r.served_steps[1:]))
            for r in fleet.readers)
        ck.close()
    finally:
        faultfs.uninstall()
    got_w = weights(store)
    detail = {
        "chunks": n_chunks,
        "degraded_publishes": degraded,
        "backlog_after_flush": backlog,
        "injected": {f"{k[0]}/{k[1]}/{k[2]}": v
                     for k, v in fs.injected_counts().items()},
        "rejected_candidates": sum(r.watcher.rejected
                                   for r in fleet.readers),
        "poll_errors": poll_errors,
        "fence_trail": fence_trail[-6:],
        "violations": violations,
        "converged": converged,
        "final_step": final_step,
        "weights_bit_identical": bool(np.array_equal(got_w, want_w)),
        "snapshot_bit_identical": bool(np.array_equal(
            np.asarray(snap_tables["weights"]), want_w)),
    }
    ok = (not violations and converged and served_monotone
          and degraded >= 1 and backlog == 0
          and poll_errors > 0
          and final_step == n_chunks
          and detail["weights_bit_identical"]
          and detail["snapshot_bit_identical"]
          and len(fence_trail) >= 2)
    return ok, detail


def run_storage_blackout_recover_scenario(tmpdir: str, *,
                                          timeout: float = 600):
    """Total storage BLACKOUT mid-run: every snapshot write fails for a
    window covering three consecutive publishes' full retry budgets,
    then storage recovers. Training must survive with a BOUNDED publish
    backlog (exactly the blacked-out publishes, drained to 0 at the
    first landed one), finish bit-identical to the fault-free run, and
    leave a directory whose newest snapshot holds the same state the
    clean run published — then actually RESUME from it."""
    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.testing import faultfs
    from fps_tpu.testing.faultfs import FaultRule
    from fps_tpu.testing.workloads import weights

    _mesh, chunks, make_trainer = _storage_harness()
    n_chunks = len(chunks)

    # Clean arm.
    d_clean = os.path.join(tmpdir, "clean")
    trainer, store, tables, ls = make_trainer()
    ck = AsyncCheckpointer(d_clean, keep=n_chunks + 2)
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                       checkpointer=ck, checkpoint_every=1)
    ck.close()
    want_w = weights(store).copy()
    _, clean_snap, _, _ = AsyncCheckpointer(
        d_clean, keep=n_chunks + 2).read_snapshot(n_chunks)

    # Blackout arm: publishes 3, 4, 5 each exhaust their 4-attempt
    # budget (ops 2..13), then the filesystem recovers.
    D = 3
    rules = [FaultRule("snapshot", "write", "errno", errno_name="EIO",
                       start=2, count=4 * D)]
    d_fault = os.path.join(tmpdir, "blackout")
    trainer, store, tables, ls = make_trainer()
    fs = faultfs.install(rules, seed=0)
    try:
        ck = AsyncCheckpointer(d_fault, keep=n_chunks + 2)
        tables, ls, _ = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1),
            checkpointer=ck, checkpoint_every=1)
        ck.flush()
        degraded = ck.degraded_publishes
        backlog = ck._publish_backlog
        final_step = ck.latest_valid_step()
        _, fault_snap, _, _ = ck.read_snapshot(final_step)
        ck.close()
    finally:
        faultfs.uninstall()
    got_w = weights(store)

    # Resume leg: the recovered directory is a real restart point.
    trainer2, store2, t2, l2 = make_trainer()
    ck2 = AsyncCheckpointer(d_fault, keep=n_chunks + 2)
    _t, _l, step = trainer2.restore_checkpoint(ck2, l2)
    ck2.close()
    resumed_w = weights(store2)
    detail = {
        "chunks": n_chunks,
        "degraded_publishes": degraded,
        "backlog_after_flush": backlog,
        "injected": {f"{k[0]}/{k[1]}/{k[2]}": v
                     for k, v in fs.injected_counts().items()},
        "final_step": final_step,
        "restored_step": step,
        "weights_bit_identical": bool(np.array_equal(got_w, want_w)),
        "snapshot_bit_identical": bool(np.array_equal(
            np.asarray(fault_snap["weights"]),
            np.asarray(clean_snap["weights"]))),
        "resume_bit_identical": bool(np.array_equal(resumed_w, got_w)),
    }
    ok = (degraded == D and backlog == 0
          and final_step == n_chunks and step == n_chunks
          and detail["weights_bit_identical"]
          and detail["snapshot_bit_identical"]
          and detail["resume_bit_identical"])
    return ok, detail


def run_enospc_compaction_scenario(tmpdir: str, *, timeout: float = 600):
    """ENOSPC mid-compaction: the LSM fold's full-snapshot write fails
    through its whole retry budget. The fold must ABORT without
    touching the chain (every link still resolves, reads serve the
    resolved head), storage.compaction_aborts counts it, and — after
    recovery — the next publish re-triggers the compaction, which
    completes and preserves the state bit-exactly."""
    import numpy as np

    from fps_tpu import obs
    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.core.checkpoint import (
        Checkpointer,
        DeltaPolicy,
        load_rows,
    )

    from fps_tpu.testing import faultfs
    from fps_tpu.testing.faultfs import FaultRule

    _mesh, _chunks, make_trainer = _storage_harness()
    trainer, store, tables, ls = make_trainer()
    rec = obs.Recorder(sinks=[])
    obs.events.set_default_recorder(rec)
    d = os.path.join(tmpdir, "enospc")
    # Writes by op index: save1 full (0), save2 delta (1), save3 delta
    # (2) -> auto-compaction (compact_every=2) writes the fold at ops
    # 3..6 (4 attempts, all ENOSPC -> abort); save4's delta is op 7
    # (lands), and ITS auto-compaction at op 8 succeeds. Saves perturb
    # a HANDFUL of rows each, so the publications really are row-sparse
    # deltas (a whole-table change would publish fulls and never build
    # a chain to fold).
    rules = [FaultRule("snapshot", "write", "errno",
                       errno_name="ENOSPC", start=3, count=4)]
    fs = faultfs.install(rules, seed=0)
    spec = store.specs["weights"]
    rng = np.random.default_rng(0)
    try:
        ck = Checkpointer(d, keep=20,
                          delta=DeltaPolicy(full_every=10,
                                            compact_every=2))
        state_at = {}
        for i in range(4):
            ids = np.arange(i * 4, i * 4 + 4) % spec.num_ids
            load_rows(store, "weights", ids,
                      rng.normal(size=(len(ids), spec.dim))
                      .astype(np.float32))
            ck.save(i + 1, store, None)
            state_at[i + 1] = store.dump_model("weights")[1].copy()
            if i + 1 == 3:
                # The fold at step 3 just aborted: chain must be
                # intact and resolvable.
                pubs = fmt.publications(d)
                kinds_mid = {s: p.kind for s, p in pubs.items()}
                aborted = ck.compactions == 0
                resolved_mid = fmt.latest_valid_chain(d)
                mid_ok = (resolved_mid is not None
                          and resolved_mid[0] == 3)
    finally:
        faultfs.uninstall()
        obs.events.set_default_recorder(None)
    aborts = int(rec.counter_value("storage.compaction_aborts"))
    pubs = fmt.publications(d)
    resolved = fmt.latest_valid_chain(d)
    head_ok = (resolved is not None and resolved[0] == 4
               and resolved[1][-1].kind == "full")
    state_ok = False
    if resolved is not None:
        entries = fmt.resolve_chain_entries(resolved[1])
        state_ok = bool(np.array_equal(
            np.asarray(entries["table::weights"]), state_at[4]))
    detail = {
        "kinds_mid_abort": {str(k): v for k, v in
                            sorted(kinds_mid.items())},
        "mid_abort_resolvable": mid_ok,
        "compaction_aborts_counted": aborts,
        "compactions_completed": ck.compactions,
        "injected": {f"{k[0]}/{k[1]}/{k[2]}": v
                     for k, v in fs.injected_counts().items()},
        "final_kinds": {str(s): p.kind for s, p in sorted(pubs.items())},
        "head_is_compacted_full": head_ok,
        "state_bit_exact": state_ok,
    }
    ok = (aborted and mid_ok and aborts >= 1
          and ck.compactions >= 1 and head_ok and state_ok)
    return ok, detail


def run_slow_lease_near_ttl_scenario(tmpdir: str, *,
                                     timeout: float = 600):
    """A live leader's lease renewals hit injected slow writes (1.2s
    against a 2s TTL, two consecutive renewals — one isolated spike is
    tolerated by design): the holder must STEP DOWN cleanly before its
    own record expires (a slow filesystem must never let a leader
    silently blow its TTL), stop renewing so the record lapses on
    schedule, and a follower must seize with a strictly-higher
    (monotone) fencing epoch — after which the deposed leader stays
    out."""
    import time as _time

    from fps_tpu.supervise.pod import Lease
    from fps_tpu.testing import faultfs
    from fps_tpu.testing.faultfs import FaultRule

    TTL = 2.0
    path = os.path.join(tmpdir, "pod_lease.json")
    A = Lease(path, "hA", TTL)
    B = Lease(path, "hB", TTL)
    A.tick()  # claim
    held, rec, _ = A.tick()  # confirm
    if not held:
        return False, {"error": "A never acquired the lease"}
    epoch_a = int(rec["epoch"])
    # Lease writes so far: A's claim (op 0). The next renewals (ops
    # 1..2) are slowed past TTL/2.
    fs = faultfs.install([FaultRule("lease", "replace", "delay",
                                    delay_s=0.6 * TTL, start=1,
                                    count=2)])
    try:
        stepped_at = None
        last_landed_t = float(rec["t"])
        deadline = _time.monotonic() + min(timeout, 30.0)
        while _time.monotonic() < deadline:
            held, rec, _ = A.tick()
            if held:
                last_landed_t = float(rec["t"])
            else:
                # The step-down tick's own (slow-landed) renewal still
                # counts as the freshest landed record — expiry runs
                # from ITS timestamp.
                if rec and rec.get("host") == "hA":
                    last_landed_t = float(rec["t"])
                stepped_at = _time.time()
                break
            _time.sleep(0.05)
        if stepped_at is None:
            return False, {"error": "A never stepped down"}
        # Stepped down BEFORE its record's expiry.
        before_expiry = stepped_at < last_landed_t + TTL
        # B seizes after the record lapses, with a monotone epoch bump.
        seized_epoch = None
        deadline = _time.monotonic() + min(timeout, 30.0)
        while _time.monotonic() < deadline:
            held_b, rec_b, _ = B.tick()
            if held_b:
                seized_epoch = int(rec_b["epoch"])
                seized_at = _time.time()
                break
            _time.sleep(0.05)
        if seized_epoch is None:
            return False, {"error": "B never seized the lease"}
        # The deposed leader stays out while B renews (and its stale
        # epoch can never regress the record for any observer).
        stays_out = True
        regress = False
        for _ in range(6):
            held_a, rec_a, _ = A.tick()
            stays_out = stays_out and not held_a
            B.tick()
            cur = B.read() or {}
            if int(cur.get("epoch", seized_epoch)) < seized_epoch:
                regress = True
            _time.sleep(0.05)
    finally:
        faultfs.uninstall()
    detail = {
        "ttl_s": TTL,
        "leader_epoch": epoch_a,
        "stepdowns": A.stepdowns,
        "renew_failures": A.renew_failures,
        "stepped_down_before_expiry": before_expiry,
        "stepdown_to_seizure_s": round(seized_at - stepped_at, 3),
        "seized_epoch": seized_epoch,
        "epoch_monotone": seized_epoch > epoch_a and not regress,
        "deposed_stays_out": stays_out,
    }
    ok = (A.stepdowns >= 1 and before_expiry
          and seized_epoch > epoch_a and not regress and stays_out
          and seized_at > stepped_at)
    return ok, detail


# ---------------------------------------------------------------------------
# Hostile-network scenarios (fps_tpu.serve.wire + fps_tpu.testing.faultnet;
# docs/resilience.md "Hostile network"): deterministic wire-fault schedules
# against the framed TCP plane — torn frames, refused reconnects, slow
# peers, one-way partitions — with the framing gates, retry budgets, replay
# cache, and per-reader liveness all required to hold.
# ---------------------------------------------------------------------------

def _wire_harness():
    """One fixed snapshot behind a fresh ReadServer + the deterministic
    request sequence the net scenarios replay: returns
    ``(make_server, requests)``. Same snapshot and sequence every call,
    so the clean run's responses are the bit-identity reference."""
    import numpy as np

    from fps_tpu.serve import ReadServer, ServableSnapshot

    rng = np.random.default_rng(3)
    tables = {"weights": rng.normal(size=(256, 8)).astype(np.float32)}
    reqs = [{"op": "pull", "table": "weights",
             "ids": rng.integers(0, 256, 16).tolist()}
            for _ in range(60)]

    def make_server():
        server = ReadServer()
        server.swap_to(ServableSnapshot(11, "net-scenario", tables, [],
                                        "none"))
        return server

    return make_server, reqs


def _fired_by_stream(trail):
    """Project an evidence trail onto per-(class, op) sublists: the
    within-stream order is deterministic even when two streams' lock
    acquisitions interleave differently across runs."""
    out: dict[tuple, list] = {}
    for cls, op, n, fault in trail:
        out.setdefault((cls, op), []).append((n, fault))
    return out


def run_net_torn_frames_scenario(tmpdir: str, *, timeout: float = 600):
    """Torn frames never decode (``fps_tpu.serve.wire``): a
    deterministic ``faultnet`` schedule cuts the client's sends
    mid-frame (and resets one outright) against a live framed server.
    The contract:

    * the server counts every torn frame and drops the connection —
      a truncated frame is NEVER decoded into a request (bit-identity
      of every response against the fault-free run is the witness);
    * the client classifies the failures as retryable, reconnects with
      backoff, and completes the whole sequence inside its budgets;
    * the schedule is REPLAYABLE: a second run with the same seed
      fires the same faults at the same per-stream operation counts
      and produces the same responses.
    """
    from fps_tpu.serve import TcpServe, WireClient
    from fps_tpu.testing import faultnet
    from fps_tpu.testing.faultnet import NetFaultRule

    make_server, reqs = _wire_harness()

    # Clean reference.
    with TcpServe(make_server()) as tcp:
        with WireClient(tcp.host, tcp.port) as wc:
            want = [wc.request(r) for r in reqs]

    rules = [
        # Mid-frame cuts on the client's sends: the server's framing
        # gates must reject every one. start=2 keeps the constructor
        # handshake clean (the ctor connects once, without retry).
        NetFaultRule("client", "send", "cut", cut_bytes=5, start=2,
                     count=None, every=9),
        NetFaultRule("client", "send", "reset", start=30, count=1),
    ]

    def faulted_run():
        net = faultnet.install(rules, seed=0)
        try:
            with TcpServe(make_server()) as tcp:
                wc = WireClient(tcp.host, tcp.port,
                                peer_class="client")
                got = [wc.request(r) for r in reqs]
                wc.close()
                return (got, net.trail(),
                        {"retries": wc.retries,
                         "reconnects": wc.reconnects},
                        tcp.wire_stats())
        finally:
            faultnet.uninstall()

    got1, trail1, client1, stats1 = faulted_run()
    got2, trail2, _client2, stats2 = faulted_run()

    cuts = sum(1 for _, _, _, f in trail1 if f == "cut")
    detail = {
        "requests": len(reqs),
        "injected": {f"{cls}/{op}": len(v)
                     for (cls, op), v in
                     _fired_by_stream(trail1).items()},
        "client": client1,
        "server_torn_frames": stats1["torn_frames"],
        "responses_bit_identical": bool(got1 == want),
        "replay_deterministic": bool(
            _fired_by_stream(trail1) == _fired_by_stream(trail2)
            and got1 == got2
            and stats1["torn_frames"] == stats2["torn_frames"]),
    }
    ok = (detail["responses_bit_identical"]
          and detail["replay_deterministic"]
          and cuts >= 3
          # Every cut the server saw was counted, none decoded.
          and stats1["torn_frames"] >= cuts
          and client1["reconnects"] >= cuts
          and client1["retries"] >= cuts)
    return ok, detail


def run_net_reconnect_storm_scenario(tmpdir: str, *,
                                     timeout: float = 600):
    """Reconnects dedupe in-flight requests (the replay cache's chaos
    invariant): the server's RESPONSE sends are cut mid-frame and the
    client's first reconnect attempts are refused outright. The
    contract:

    * every logical request EXECUTES exactly once — resends after a
      reconnect are answered from the (session, req_id) replay cache
      (``server.requests`` equals the request count; ``dedup_replays``
      is the positive witness);
    * the refused-connect storm backs off and recovers under the same
      session (responses bit-identical to the fault-free run);
    * the schedule replays deterministically.
    """
    from fps_tpu.serve import TcpServe, WireClient
    from fps_tpu.testing import faultnet
    from fps_tpu.testing.faultnet import NetFaultRule

    make_server, reqs = _wire_harness()

    with TcpServe(make_server()) as tcp:
        with WireClient(tcp.host, tcp.port) as wc:
            want = [wc.request(r) for r in reqs]

    rules = [
        # Cut the server's data sends (start=2 spares the constructor
        # HELLO_OK): the executed response dies on the wire, the client
        # resends, the replay cache answers. count is the WINDOW width
        # ([start, start+count)), so every=5 in a 25-op window fires 5
        # cuts.
        NetFaultRule("serve", "send", "cut", cut_bytes=4, start=2,
                     count=25, every=5),
        # And the first two reconnect attempts are REFUSED: the storm
        # must back off, not busy-loop.
        NetFaultRule("client", "connect", "refuse", start=1, count=2),
    ]

    def faulted_run():
        net = faultnet.install(rules, seed=0)
        try:
            server = make_server()
            with TcpServe(server) as tcp:
                wc = WireClient(tcp.host, tcp.port,
                                peer_class="client")
                got = [wc.request(r) for r in reqs]
                wc.close()
                return (got, net.trail(),
                        {"retries": wc.retries,
                         "reconnects": wc.reconnects},
                        tcp.wire_stats(), server.requests)
        finally:
            faultnet.uninstall()

    got1, trail1, client1, stats1, executed1 = faulted_run()
    got2, trail2, _c2, stats2, executed2 = faulted_run()

    cuts = sum(1 for _, _, _, f in trail1 if f == "cut")
    refused = sum(1 for _, _, _, f in trail1 if f == "refuse")
    detail = {
        "requests": len(reqs),
        "response_cuts": cuts,
        "refused_connects": refused,
        "client": client1,
        "dedup_replays": stats1["dedup_replays"],
        "executed_requests": executed1,
        "responses_bit_identical": bool(got1 == want),
        "replay_deterministic": bool(
            _fired_by_stream(trail1) == _fired_by_stream(trail2)
            and got1 == got2 and executed1 == executed2),
    }
    ok = (detail["responses_bit_identical"]
          and detail["replay_deterministic"]
          and cuts >= 3 and refused == 2
          # THE invariant: zero duplicate-applied requests.
          and executed1 == len(reqs)
          and stats1["dedup_replays"] >= 1
          and client1["reconnects"] >= 1)
    return ok, detail


def run_net_slow_peer_scenario(tmpdir: str, *, timeout: float = 600):
    """Slow peers and dead deadlines (``docs/STALENESS.md``): the
    client's sends are byte-trickled and the server's sends delayed —
    a slow peer must cost LATENCY, never integrity (zero torn frames,
    responses bit-identical). A second client then faces a total
    one-way partition (every recv times out) under a small deadline
    budget: the request must fail FAST with ``TimeoutError`` — the
    deadline is a budget, not a suggestion — while the server's
    replay cache keeps the retried sends idempotent.
    """
    import time as _time

    from fps_tpu.serve import TcpServe, WireClient
    from fps_tpu.testing import faultnet
    from fps_tpu.testing.faultnet import NetFaultRule

    make_server, reqs = _wire_harness()

    with TcpServe(make_server()) as tcp:
        with WireClient(tcp.host, tcp.port) as wc:
            want = [wc.request(r) for r in reqs]

    rules = [
        NetFaultRule("client", "send", "trickle", chunk=7,
                     delay_s=0.001, start=1, count=None, every=3),
        NetFaultRule("serve", "send", "delay", delay_s=0.001,
                     start=0, count=None, every=4),
        # The partitioned client: every recv AFTER its constructor
        # handshake times out — a one-way partition (our bytes leave,
        # theirs never arrive).
        NetFaultRule("deadline", "recv", "partition", start=1,
                     count=None),
    ]
    net = faultnet.install(rules, seed=0)
    try:
        server = make_server()
        with TcpServe(server) as tcp:
            wc = WireClient(tcp.host, tcp.port, peer_class="client")
            got = [wc.request(r) for r in reqs]
            wc.close()

            pc = WireClient(tcp.host, tcp.port, peer_class="deadline")
            t0 = _time.monotonic()
            deadline_error = None
            try:
                pc.request(reqs[0], deadline_s=0.5)
            except TimeoutError as e:
                deadline_error = repr(e)
            elapsed = _time.monotonic() - t0
            pc.close()
            stats = tcp.wire_stats()
            executed = server.requests
        trail = net.trail()
    finally:
        faultnet.uninstall()

    trickles = sum(1 for _, _, _, f in trail if f == "trickle")
    partitions = sum(1 for _, _, _, f in trail if f == "partition")
    detail = {
        "requests": len(reqs),
        "trickled_sends": trickles,
        "partitioned_recvs": partitions,
        "torn_frames": stats["torn_frames"],
        "responses_bit_identical": bool(got == want),
        "deadline_error": deadline_error,
        "deadline_elapsed_s": round(elapsed, 3),
        "client_deadline_exceeded": pc.deadline_exceeded,
        "executed_requests": executed,
    }
    ok = (detail["responses_bit_identical"]
          and trickles >= 10
          # Slow is slow, not torn: every trickled frame arrived whole.
          and stats["torn_frames"] == 0
          and deadline_error is not None
          and partitions >= 1
          and pc.deadline_exceeded >= 1
          # The budget BOUND the journey (0.5s budget, generous slack
          # for backoff rounding — nowhere near a socket timeout).
          and elapsed < 5.0
          # Idempotence held for the partitioned client's resends: at
          # most ONE execution beyond the measured sequence.
          and executed <= len(reqs) + 1)
    return ok, detail


# The SIGSTOPped-reader child: a quorum-1 FleetReader polling one
# snapshot dir, beating its liveness beacon fast (0.1s) so the scenario
# detects the wedge in seconds. Run via ``python -c`` — the serving
# plane is jax-free, so the child starts fast.
_READER_LOOP_SRC = """\
import sys, time
from fps_tpu.serve.fleet import FleetReader
r = FleetReader(sys.argv[1], sys.argv[2], quorum=1,
                heartbeat_interval_s=0.1)
while True:
    r.poll()
    time.sleep(0.05)
"""


def run_net_partition_reader_scenario(tmpdir: str, *,
                                      timeout: float = 600):
    """A partitioned (SIGSTOPped) reader becomes a ``reader_wedged``
    incident, never a silent zero (the tentpole's liveness leg): a
    reader child polls + beats against a live training run's snapshot
    dir; mid-run the child is SIGSTOPped — its beacon freezes while its
    process, from the filesystem's point of view, simply goes silent.
    The contract:

    * before the stop, the reader is LIVE (beacon fresh, no wedge —
      no false positives);
    * within the liveness timeout of the stop, ``liveness_check``
      reports the reader wedged (the incident a supervisor restarts
      on);
    * training is UNAFFECTED: final weights bit-identical to the
      fault-free run (a dead reader costs serving capacity, never
      training state);
    * after SIGCONT (the partition heals) the reader recovers: beats
      fresh again and catches up to the newest publication.
    """
    import signal
    import subprocess as sp
    import time as _time

    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.serve import liveness_check, scan_heartbeats
    from fps_tpu.testing.workloads import weights

    _mesh, chunks, make_trainer = _storage_harness()

    # Clean arm: the bit-identity reference.
    trainer, store, tables, ls = make_trainer()
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))
    want_w = weights(store).copy()

    LIVENESS = 1.5
    d = os.path.join(tmpdir, "net_partition")
    os.makedirs(d, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT
    child = sp.Popen([sys.executable, "-c", _READER_LOOP_SRC, d, "r0"],
                     env=env, cwd=_ROOT, stdout=sp.DEVNULL,
                     stderr=sp.DEVNULL)
    stopped_at = [None]
    live_before = [None]
    try:
        trainer, store, tables, ls = make_trainer()
        ck = AsyncCheckpointer(d, keep=len(chunks) + 2)

        def on_chunk(step, _metrics):
            if step != 4 or stopped_at[0] is not None:
                return
            # Never SIGSTOP a reader that hasn't come up — that would
            # test a start timeout, not a wedge.
            dl = _time.monotonic() + 60.0
            while not scan_heartbeats(d) and _time.monotonic() < dl:
                _time.sleep(0.05)
            live_before[0] = liveness_check(d, timeout_s=LIVENESS,
                                            expected=["r0"])
            os.kill(child.pid, signal.SIGSTOP)
            stopped_at[0] = _time.monotonic()

        tables, ls, _ = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1),
            checkpointer=ck, checkpoint_every=1, on_chunk=on_chunk)
        ck.flush()
        final_step = ck.latest_valid_step()
        ck.close()
        got_w = weights(store)
        if stopped_at[0] is None:
            return False, {"error": "reader was never SIGSTOPped"}

        # The wedge becomes an incident within the liveness timeout.
        wedged_at = None
        dl = _time.monotonic() + min(timeout, 60.0)
        while _time.monotonic() < dl:
            live = liveness_check(d, timeout_s=LIVENESS,
                                  expected=["r0"])
            if "r0" in live["wedged"]:
                wedged_at = _time.monotonic()
                break
            _time.sleep(0.05)
        if wedged_at is None:
            return False, {"error": "reader_wedged never fired",
                           "heartbeats": scan_heartbeats(d)}
        detect_s = wedged_at - stopped_at[0]

        # SIGCONT: the partition heals; the reader must beat fresh
        # again and converge on the newest publication.
        os.kill(child.pid, signal.SIGCONT)
        recovered = caught_up = False
        dl = _time.monotonic() + min(timeout, 60.0)
        while _time.monotonic() < dl:
            live = liveness_check(d, timeout_s=LIVENESS,
                                  expected=["r0"])
            hb = scan_heartbeats(d).get("r0")
            if "r0" not in live["wedged"] and hb is not None:
                recovered = True
                if hb.get("step") == final_step:
                    caught_up = True
                    break
            _time.sleep(0.05)
    finally:
        child.kill()
        child.wait(timeout=10)

    detail = {
        "chunks": len(chunks),
        "final_step": final_step,
        "live_before_stop": live_before[0],
        "wedge_detect_s": round(detect_s, 3),
        "liveness_timeout_s": LIVENESS,
        "recovered": recovered,
        "caught_up_to_final_step": caught_up,
        "weights_bit_identical": bool(np.array_equal(got_w, want_w)),
    }
    ok = (live_before[0] is not None
          and live_before[0]["wedged"] == []      # no false positive
          and detect_s < 30.0
          and recovered and caught_up
          and detail["weights_bit_identical"])
    return ok, detail


# ---------------------------------------------------------------------------
# Batched read-plane scenarios (ISSUE 19): the multi-lookup wire op and
# the autoscaled serving fleet under the same hostile-network /
# reader-churn treatment as everything above. Deterministic explicit
# multi batches (never the racing coalescer) so bit-identity assertions
# stay exact.
# ---------------------------------------------------------------------------

def run_serve_batch_storm_scenario(tmpdir: str, *, timeout: float = 600):
    """Batched multi frames under a wire-fault storm (the tentpole's
    coalesced read path meets PR-16's hostile network): the 60-request
    harness sequence rides in 5 ``multi`` frames whose sends are cut on
    BOTH directions. The contract:

    * a torn multi frame is NEVER partially applied — the server
      executes exactly ``len(reqs)`` sub-requests across the whole
      storm (resent frames dedupe through the replay cache as ONE
      unit, ``dedup_replays`` the positive witness);
    * batched responses are bit-identical to the fault-free batched
      run, which is itself bit-identical to the fault-free UNBATCHED
      run (batching changes framing, never answers) — and the
      zero-copy binary encoding returns the same numbers as JSON;
    * an admission-wedged server sheds the whole batch with a
      retryable BUSY inside the deadline budget, and the identical
      batch succeeds bit-identically once capacity returns;
    * the fault schedule replays deterministically.
    """
    import time as _time

    import numpy as np

    from fps_tpu.serve import ServerBusyError, TcpServe, WireClient
    from fps_tpu.serve.wire import CAP_BIN, CAP_MULTI
    from fps_tpu.testing import faultnet
    from fps_tpu.testing.faultnet import NetFaultRule

    make_server, reqs = _wire_harness()
    batches = [reqs[i:i + 12] for i in range(0, len(reqs), 12)]

    # Clean references: solo, batched-JSON, batched-binary — all three
    # must agree bitwise before any fault is injected.
    with TcpServe(make_server()) as tcp:
        with WireClient(tcp.host, tcp.port) as wc:
            want_solo = [wc.request(r) for r in reqs]
            want = [wc.multi(b) for b in batches]
        with WireClient(tcp.host, tcp.port,
                        caps=(CAP_MULTI, CAP_BIN)) as wb:
            bin_granted = CAP_BIN in wb.caps
            got_bin = [wb.multi(b) for b in batches]
        clean_stats = tcp.wire_stats()
    flat = [r for batch in want for r in batch]
    flat_bin = [r for batch in got_bin for r in batch]
    bin_matches_json = bin_granted and all(
        b["ok"] and b["step"] == j["step"]
        and np.array_equal(np.asarray(j["values"], np.float32),
                           np.asarray(b["values"]))
        for j, b in zip(flat, flat_bin))

    rules = [
        # Cut the client's multi sends (start=2 spares the ctor HELLO):
        # every torn frame must be rejected whole, resent whole, and
        # applied once.
        NetFaultRule("client", "send", "cut", cut_bytes=9, start=2,
                     count=None, every=3),
        # And cut the server's response sends inside an early window:
        # the executed batch's response dies on the wire, the resend is
        # answered from the replay cache as one unit.
        NetFaultRule("serve", "send", "cut", cut_bytes=4, start=3,
                     count=8, every=4),
    ]

    def faulted_run():
        net = faultnet.install(rules, seed=0)
        try:
            server = make_server()
            with TcpServe(server) as tcp:
                wc = WireClient(tcp.host, tcp.port,
                                peer_class="client")
                got = [wc.multi(b) for b in batches]
                wc.close()
                return (got, net.trail(),
                        {"retries": wc.retries,
                         "reconnects": wc.reconnects},
                        tcp.wire_stats(), server.requests)
        finally:
            faultnet.uninstall()

    got1, trail1, client1, stats1, executed1 = faulted_run()
    got2, trail2, _c2, stats2, executed2 = faulted_run()
    client_cuts = len([1 for (cls, _op), v in
                       _fired_by_stream(trail1).items()
                       if cls == "client" for _ in v])

    # BUSY leg on a clean network: wedge the admission budget, the
    # whole batch sheds retryably inside its deadline; release, and the
    # identical batch answers bit-identically.
    with TcpServe(make_server()) as tcp:
        with WireClient(tcp.host, tcp.port) as wc:
            assert tcp.admission.try_admit(tcp.admission.max_cost)
            shed_error = None
            t0 = _time.monotonic()
            try:
                wc.multi(batches[0], deadline_s=0.4)
            except ServerBusyError as e:
                shed_error = repr(e)
            shed_elapsed = _time.monotonic() - t0
            tcp.admission.release(tcp.admission.max_cost)
            after_release = wc.multi(batches[0])
            shed_stats = tcp.wire_stats()

    detail = {
        "requests": len(reqs),
        "batches": len(batches),
        "injected": {f"{cls}/{op}": len(v)
                     for (cls, op), v in
                     _fired_by_stream(trail1).items()},
        "client": client1,
        "server_torn_frames": stats1["torn_frames"],
        "multi_frames": stats1["multi_frames"],
        "dedup_replays": stats1["dedup_replays"],
        "executed_subrequests": executed1,
        "clean_multi_frames": clean_stats["multi_frames"],
        "bin_responses_clean": clean_stats["bin_responses"],
        "batched_equals_unbatched": bool(flat == want_solo),
        "bin_matches_json": bool(bin_matches_json),
        "responses_bit_identical": bool(got1 == want),
        "replay_deterministic": bool(
            _fired_by_stream(trail1) == _fired_by_stream(trail2)
            and got1 == got2 and executed1 == executed2),
        "shed_error": shed_error,
        "shed_elapsed_s": round(shed_elapsed, 3),
        "shed_requests": shed_stats["shed_requests"],
        "after_release_bit_identical": bool(after_release == want[0]),
    }
    ok = (detail["batched_equals_unbatched"]
          and detail["bin_matches_json"]
          and detail["responses_bit_identical"]
          and detail["replay_deterministic"]
          and client_cuts >= 3
          and stats1["torn_frames"] >= 1
          # THE invariant: a torn multi frame is never partially
          # applied and a resent one never double-applied.
          and executed1 == len(reqs)
          and stats1["dedup_replays"] >= 1
          and stats1["multi_frames"] >= len(batches)
          and clean_stats["bin_responses"] >= 1
          and shed_error is not None
          and shed_stats["shed_requests"] >= 1
          and shed_elapsed < 5.0
          and detail["after_release_bit_identical"])
    return ok, detail


def run_autoscale_reader_churn_scenario(tmpdir: str, *,
                                        timeout: float = 600):
    """The autoscaler survives reader churn with a monotone fence (the
    tentpole's capacity leg): a 2-reader fleet over a real snapshot dir
    scales to ``max_readers`` under latency burn, absorbs a publish
    train, REPLACES an alive-but-silent wedged reader without ever
    dipping below size, and scales back down to ``min_readers`` when
    the burn ends. The contract:

    * every scale decision is journaled with its evidence
      (``decisions`` trail: scale_up, replace, scale_down all fire);
    * the shared step fence NEVER regresses across the whole churn
      (sampled continuously) and lands on the last published step;
    * the wedged reader's replacement catches up to the fence and
      answers bit-identically to the published table — capacity
      changes reframe the fleet, never the answers;
    * the fleet never shrinks below ``min_readers`` and quorum follows
      membership (majority of the current fleet).
    """
    import threading as _threading
    import time as _time

    import numpy as np

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.serve import ReadAutoscaler, ServingFleet

    rng = np.random.default_rng(7)
    steps = 5
    tables = [rng.normal(size=(64, 4)).astype(np.float32)
              for _ in range(steps)]
    d = os.path.join(tmpdir, "autoscale_churn")
    os.makedirs(d, exist_ok=True)

    def publish(step):
        arrays = {"table::w": tables[step - 1],
                  "meta::ls_format": np.array("exported")}
        for k in list(arrays):
            arrays["meta::crc::" + k] = np.uint32(
                fmt.array_crc32(arrays[k]))
        np.savez(fmt.snapshot_path(d, step), **arrays)

    def wait_for(pred, budget):
        dl = _time.monotonic() + min(timeout, budget)
        while _time.monotonic() < dl:
            if pred():
                return True
            _time.sleep(0.02)
        return pred()

    publish(1)
    fleet = ServingFleet(d, 2)  # auto-quorum: majority of the fleet
    scaler = ReadAutoscaler(
        fleet, min_readers=2, max_readers=4,
        latency_slo_s=1e-6,      # any real request burns the SLO
        fence_lag_slo_steps=64.0, cooldown_s=0.0,
        liveness_timeout_s=2.5)
    ids = list(range(0, 64, 5))
    fence_trail: list[tuple[int, int]] = []
    stop_sampler = _threading.Event()

    def sample_fence():
        fence = fleet.readers[0].fence
        while not stop_sampler.is_set():
            f = fence.read()
            if f is not None:
                fence_trail.append(f)
            _time.sleep(0.005)

    sampler = _threading.Thread(target=sample_fence, daemon=True)
    fleet.start(interval_s=0.02)
    sampler.start()
    try:
        if not wait_for(lambda: all(
                r.stats()["step"] == 1 for r in fleet.readers), 60.0):
            return False, {"error": "initial fleet never converged",
                           "stats": fleet.stats()}

        # Latency burn: real pulls through every reader's server put a
        # real p99 over the (microscopic) SLO; the fence is fresh, so
        # the scaler must add capacity up to max_readers.
        from fps_tpu.serve import NoSnapshotError
        sizes = []
        for _ in range(8):
            for r in list(fleet.readers):
                for _i in range(5):
                    try:
                        r.server.pull("w", ids)
                    except NoSnapshotError:
                        break  # still booting: no latency sample yet
            decision = scaler.evaluate(newest_step=1)
            sizes.append(decision["fleet_size"])
            if decision["fleet_size"] >= 4:
                break
        scaled_up = len(fleet.readers) == 4 and fleet.quorum == 3

        # Publish train: the grown fleet's fence must walk 2..5
        # monotonically (the sampler is watching for any regression).
        for step in range(2, steps + 1):
            publish(step)
        if not wait_for(lambda: all(
                r.stats()["step"] == steps for r in fleet.readers),
                60.0):
            return False, {"error": "fleet never reached the last "
                                    "publish", "stats": fleet.stats()}

        # Wedge one reader alive-but-silent: its polling thread keeps
        # cycling but the beacon freezes — the scaler must REPLACE it
        # (join a fresh reader first, retire the ghost after).
        victim = fleet.readers[1].reader_id
        fleet.readers[1].poll = lambda: None  # instance-attr shadow
        replaced = None

        def try_replace():
            nonlocal replaced
            decision = scaler.evaluate(newest_step=steps)
            if decision["action"] == "replace":
                replaced = decision
            return replaced is not None

        if not wait_for(try_replace, 30.0):
            return False, {"error": "wedged reader never replaced",
                           "decisions": scaler.decisions[-3:]}
        replacement = replaced["replaced"][0]["replacement"]
        if not wait_for(lambda: all(
                r.stats()["step"] == steps for r in fleet.readers),
                60.0):
            return False, {"error": "replacement never caught up",
                           "stats": fleet.stats()}
        # Membership right after the replace (scale-down below may
        # legitimately retire the newest reader — the replacement).
        post_replace_ids = [r.reader_id for r in fleet.readers]

        # The burn ends: with the SLO now generous, the scaler retires
        # readers one per pass down to min_readers, then holds.
        scaler.latency_slo_s = 1e6
        down_actions = []
        for _ in range(4):
            down_actions.append(scaler.evaluate(newest_step=steps))
        final_actions = [dec["action"] for dec in down_actions]

        # Bit-identity: every surviving reader answers the last
        # published table exactly.
        answers_exact = all(
            np.array_equal(r.server.pull("w", ids)[1],
                           tables[-1][np.asarray(ids)])
            for r in fleet.readers)
        final_size = len(fleet.readers)
        final_quorum = fleet.quorum
    finally:
        stop_sampler.set()
        sampler.join(timeout=5)
        fleet.stop()

    fence_steps = [s for _e, s in fence_trail]
    fence_monotone = all(a <= b for a, b in
                         zip(fence_steps, fence_steps[1:]))
    actions = [dec["action"] for dec in scaler.decisions]
    detail = {
        "published_steps": steps,
        "scale_up_sizes": sizes,
        "scaled_to_max": scaled_up,
        "replaced": replaced["replaced"] if replaced else None,
        "replacement_in_fleet": replacement in post_replace_ids,
        "victim_gone": victim not in post_replace_ids,
        "down_actions": final_actions,
        "final_size": final_size,
        "final_quorum": final_quorum,
        "fence_samples": len(fence_trail),
        "fence_monotone": fence_monotone,
        "fence_final_step": fence_steps[-1] if fence_steps else None,
        "answers_bit_identical": bool(answers_exact),
        "actions_seen": sorted(set(actions)),
    }
    ok = (scaled_up
          and replaced is not None
          and detail["replacement_in_fleet"]
          and detail["victim_gone"]
          and final_actions.count("scale_down") == 2
          and final_size == 2 and final_quorum == 2
          and final_actions[-1] == "hold"   # never below min_readers
          and fence_monotone
          and detail["fence_final_step"] == steps
          and answers_exact
          and {"scale_up", "replace", "scale_down"} <= set(actions))
    return ok, detail


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="supervised tiny-logreg child (fps_tpu.supervise demo)")
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True,
                    help="final weights .npz (written on success)")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--examples", type=int, default=2000)
    ap.add_argument("--wedge-at", type=int, default=None,
                    help="wedge after this chunk trains, before its "
                         "checkpoint lands (once, via marker file)")
    ap.add_argument("--wedge-mode", default="sigstop",
                    choices=["sigstop", "sleep"])
    ap.add_argument("--crash-at", type=int, default=None,
                    help="exit(3) at this chunk on every attempt not "
                         "carrying it in the quarantine set")
    ap.add_argument("--always", action="store_true",
                    help="misbehave on every attempt (no marker)")
    ap.add_argument("--sync-checkpointer", action="store_true",
                    help="use the blocking Checkpointer instead of the "
                         "async writer")
    ap.add_argument("--prefetch", type=int, default=0,
                    help="overlapped host pipeline depth "
                         "(TrainerConfig.prefetch)")
    ap.add_argument("--kill-prefetch-at", type=int, default=None,
                    help="SIGKILL while the prefetch worker assembles "
                         "this (global) chunk index — once, via marker "
                         "file, unless --always")
    ap.add_argument("--kill-at", type=int, default=None,
                    help="SIGKILL after this chunk trains (async writer "
                         "flushed first), before its checkpoint lands — "
                         "once, via marker file, unless --always")
    ap.add_argument("--megastep", type=int, default=0,
                    help="device-resident megastep mode "
                         "(Trainer.run_megastep): train through the "
                         "device-ingest path with this many chunks "
                         "fused per compiled dispatch; checkpoints land "
                         "at megastep boundaries and --kill-at counts "
                         "megasteps")
    ap.add_argument("--hot-tier", type=int, default=0,
                    help="two-tier storage: replicate the leading H ids "
                         "(TableSpec.hot_tier)")
    ap.add_argument("--hot-sync-every", type=int, default=1,
                    help="hot-tier reconcile cadence in steps "
                         "(TrainerConfig.hot_sync_every)")
    ap.add_argument("--retier-every", type=int, default=0,
                    help="adaptive tiering (fps_tpu.tiering): attach a "
                         "Retierer checking every N chunk boundaries "
                         "with FORCED re-ranks (churn threshold -1) and "
                         "tracker sidecars beside the checkpoints; "
                         "combine with --hot-tier/--hot-sync-every for "
                         "the mapped tier")
    ap.add_argument("--cold-budget", type=int, default=0,
                    help="payload-proportional cold routing "
                         "(TableSpec.cold_budget; needs a partial "
                         "--hot-tier)")
    ap.add_argument("--hot-fold", default=None,
                    choices=["adagrad", "adam"],
                    help="stateful hot-tier server optimizer "
                         "(ServerLogic.hot_fold; needs a fully-"
                         "replicated --hot-tier and --hot-sync-every "
                         "> 1) — its sharded state rides checkpoints "
                         "as fold:: arrays")
    ap.add_argument("--chunk-sleep-s", type=float, default=0.0,
                    help="sleep this long at every chunk boundary — "
                         "paces the run so pod chaos scenarios can land "
                         "their faults while the children are "
                         "demonstrably mid-run (pure wall-clock, no "
                         "effect on the math)")
    ap.add_argument("--keep", type=int, default=3,
                    help="snapshot retention (Checkpointer keep). Pod "
                         "scenarios raise it: a coordinated restart "
                         "rolls every member back to the POD-COMMON "
                         "step, which a fast member's default retention "
                         "may already have collected")
    ap.add_argument("--misbehave-host", default=None,
                    help="apply the wedge/crash/kill flags only when "
                         "running as this pod member (FPS_TPU_POD_HOST) "
                         "— one pod command template, one poisoned host")
    ap.add_argument("--crash-until-file", default=None,
                    help="exit(3) at startup (before any beat) until "
                         "this file exists — the flapping member an "
                         "elastic pod must evict and later re-admit")
    ap.add_argument("--obs-dir", default=None,
                    help="open full on-disk telemetry here "
                         "(fps_tpu.obs.open_run): run journal + event "
                         "log, with the causal-trace context inherited "
                         "from the supervisor env contract — the pod "
                         "chaos scenarios point tools/trace_export.py "
                         "and the fleet rollups at these")
    ap.add_argument("--num-features", type=int, default=0,
                    help="override the workload's feature-table size "
                         "(0 = the standard tiny NF). The delta-chain "
                         "scenarios raise it so per-chunk touched rows "
                         "are a small fraction of the table and delta "
                         "publications actually engage")
    ap.add_argument("--delta-full-every", type=int, default=0,
                    help="delta-snapshot chains (DeltaPolicy.full_every "
                         "> 1): publish row-sparse deltas between "
                         "fulls, sourced from the driver's touched-rows "
                         "tracker")
    ap.add_argument("--delta-compact-every", type=int, default=0,
                    help="DeltaPolicy.compact_every: background "
                         "LSM-style chain compaction once the live "
                         "chain carries this many deltas")
    args = ap.parse_args(argv)

    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer, Checkpointer
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.resilience import RollbackPolicy
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.supervise import child
    from fps_tpu.testing import chaos
    from fps_tpu.testing.workloads import (
        NF,
        logreg_chunks,
        logreg_data,
        weights,
    )

    hb = child.from_env()
    preset = child.quarantined_from_env()
    attempt = child.attempt_from_env()
    pod = child.pod_env()

    # Pod-member misbehavior gating: one shared command template, and
    # only the named member actually misbehaves.
    misbehave = (args.misbehave_host is None
                 or pod["host"] == args.misbehave_host)
    if not misbehave:
        args.wedge_at = args.crash_at = args.kill_at = None
        args.kill_prefetch_at = args.crash_until_file = None
    if (args.crash_until_file is not None
            and not os.path.exists(args.crash_until_file)):
        # Dies before any beat or jax import: the leader sees an
        # index-less crash (never quarantinable) and, past the member's
        # eviction budget, re-plans the pod without this host.
        print(json.dumps({"event": "demo_crash_until_file",
                          "file": args.crash_until_file}), flush=True)
        return 3

    # A heartbeat-only recorder makes the DRIVER's sub-phase beats
    # (prefetch/ingest/dispatch, with a phase field) flow: without it the
    # only beats are this file's chunk-boundary ones and the supervisor
    # would record last_phase=null for every mid-chunk death. With
    # --obs-dir the full on-disk recorder opens instead (run journal +
    # event log, trace context from the supervisor env) and the
    # heartbeat sink rides it.
    rec = None
    if args.obs_dir:
        import fps_tpu.obs as obs

        rec = obs.open_run(
            args.obs_dir,
            config={"examples": args.examples, "epochs": args.epochs},
            meta={k: v for k, v in
                  (("host", pod["host"]),
                   ("workload", "supervised_demo"),
                   ("attempt", attempt)) if v is not None})
        if hb is not None:
            rec.sinks.append(child.HeartbeatSink(hb))
    elif hb is not None:
        from fps_tpu.obs import Recorder

        rec = Recorder(sinks=[child.HeartbeatSink(hb)])

    mesh = make_ps_mesh()
    W = num_workers_of(mesh)
    nf = args.num_features or NF
    if nf == NF:
        train, _ = logreg_data(args.examples)
    else:
        # Same planted workload at a custom table size (the delta-chain
        # scenarios need touched-rows << table, which tiny NF can't
        # give); same seeds, so straight vs supervised stay comparable.
        from fps_tpu.utils.datasets import (
            synthetic_sparse_classification,
            train_test_split,
        )

        data = synthetic_sparse_classification(args.examples, nf, 8,
                                               seed=7, noise=0.05)
        data = dict(data, label=(data["label"] > 0).astype(np.float32))
        train, _ = train_test_split(data)
    chunks = logreg_chunks(train, W, epochs=args.epochs)

    cfg = LogRegConfig(num_features=nf, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg)
    if args.prefetch:
        import dataclasses

        trainer.config = dataclasses.replace(trainer.config,
                                             prefetch=args.prefetch)
    # One tier-enable implementation repo-wide (validation + the
    # push_delay-conflict check included).
    from fps_tpu.examples.common import apply_hot_tier

    apply_hot_tier(args, trainer, store)
    if args.retier_every:
        from fps_tpu.tiering import Retierer

        # Forced-cadence adaptive mode: re-rank on every check, tracker
        # state persisted beside the checkpoints so a supervised restart
        # replays the straight run's re-rank decisions bit-for-bit.
        trainer.retierer = Retierer(check_every=args.retier_every,
                                    churn_threshold=-1.0,
                                    state_dir=args.ckpt_dir)
    tables, ls = trainer.init_state(jax.random.key(0))

    ckpt_cls = Checkpointer if args.sync_checkpointer else AsyncCheckpointer
    delta_policy = None
    if args.delta_full_every > 1:
        from fps_tpu.core.checkpoint import DeltaPolicy

        delta_policy = DeltaPolicy(
            full_every=args.delta_full_every,
            compact_every=args.delta_compact_every)
    # Under a pod, publishes carry (and are fenced by) this child's
    # attempt epoch — a zombie of an aborted pod attempt dies loudly on
    # its next save instead of leaking state into the new attempt.
    ckpt = ckpt_cls(args.ckpt_dir, keep=args.keep,
                    fence_epoch=pod["epoch"], delta=delta_policy)
    if pod["step"] is not None:
        # Pod-commanded COMMON restart step: prefer it exactly, fall back
        # to the newest verified snapshot at-or-below it (retention may
        # have advanced past a very old command), then to whatever this
        # member has — replica determinism makes any of these converge.
        commanded = pod["step"]
        if commanded and ckpt.verify_snapshot(commanded):
            start = commanded
        else:
            below = [s for s in ckpt.steps()
                     if s <= commanded and ckpt.verify_snapshot(s)]
            start = below[-1] if below else (ckpt.latest_valid_step() or 0)
        if start:
            tables, ls, start = trainer.restore_checkpoint(
                ckpt, ls, step=start)
    else:
        start = ckpt.latest_valid_step() or 0
        if start:
            # Auto-resolve (step=None): a corrupt newest snapshot is
            # quarantined and the restore falls back — the supervised
            # scenarios' torn-candidate contract.
            tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
    tiering_restored = None
    if start and trainer.retierer is not None:
        tiering_restored = trainer.retierer.restore(start)
    if hb is not None:
        # Beat-before-work: name the chunk about to be attempted BEFORE
        # attempting it, so a crash inside the very first (resumed) chunk
        # still attributes to it — without this, every resumed attempt
        # dies index-less and the supervisor can never quarantine a
        # deterministic mid-chunk poison (it would burn the whole retry
        # budget instead).
        hb.beat(index=start, attempt=attempt)
    meta = {"attempt": attempt, "restored_step": start,
            "quarantined": sorted(preset), "total_chunks": len(chunks),
            "pod": pod}
    print(json.dumps({"event": "demo_start", **meta}), flush=True)

    marker = os.path.join(args.ckpt_dir, "misbehave.done")
    wedge = None
    if args.wedge_at is not None:
        wedge = chaos.wedge_at_chunk(
            args.wedge_at, args.wedge_mode,
            marker=None if args.always else marker,
        )
    killer = None
    if args.kill_at is not None:
        # Flush first so the scenario's ≤1-chunk-lost bound holds under
        # the async writer (same reasoning as the wedge's flush below).
        killer = chaos.kill_at_chunk(
            args.kill_at,
            marker=None if args.always else os.path.join(
                args.ckpt_dir, "kill_at.done"),
            before=ckpt.flush,
        )

    def on_chunk(i, metrics):
        if args.chunk_sleep_s:
            import time as _time

            _time.sleep(args.chunk_sleep_s)
        # The last beat before this point named chunk i (beat-before-work:
        # the post-restore beat, or the previous boundary's i-1 -> i).
        if (args.crash_at is not None and i == args.crash_at
                and i not in preset
                and (args.always or not os.path.exists(marker))):
            # A deterministic poison batch crashing the worker at chunk
            # i: dying BEFORE beating i+1 leaves i as the attempt's
            # last_index — the supervisor's quarantine evidence. No
            # marker touch — unlike the wedge, this MUST recur until
            # quarantined.
            print(json.dumps({"event": "demo_crash", "index": int(i)}),
                  flush=True)
            sys.stdout.flush()
            os._exit(3)
        if wedge is not None and i == args.wedge_at:
            # The scenario's exact ≤1-chunk-lost bound (restored_step ==
            # wedge_at) needs prior snapshots DURABLE before the freeze —
            # the async writer may still hold the latest save in flight,
            # and a SIGSTOP'd writer never finishes. The wedge models a
            # stall between chunks, so flushing first is faithful; a real
            # mid-write freeze is covered by victim-async-midwrite (the
            # bound there is the bit-identity contract, not a fixed step).
            ckpt.flush()
        if wedge is not None:
            wedge(i, metrics)
        if killer is not None:
            killer(i, metrics)
        if hb is not None:
            hb.beat(index=int(i) + 1, attempt=attempt)

    if args.megastep:
        # Device-resident megastep path (fps_tpu.core.megastep): the
        # same logreg workload through device ingest, K chunks fused
        # per dispatch, checkpoints at megastep boundaries. The
        # --kill-at hook fires in on_megastep — after megastep i
        # trains, before its checkpoint lands — so restored_step == i
        # proves exactly one megastep was lost and replayed from the
        # last window-boundary snapshot.
        import dataclasses

        from fps_tpu.core.device_ingest import (
            DeviceDataset,
            DeviceEpochPlan,
        )

        trainer.config = dataclasses.replace(
            trainer.config, max_steps_per_call=MEGASTEP_T_CALL)
        plan = DeviceEpochPlan(
            DeviceDataset(mesh, train), num_workers=W, local_batch=32,
            seed=3)
        rollback = RollbackPolicy(preset=preset) if preset else None
        tables, ls, _ = trainer.run_megastep(
            tables, ls, plan, jax.random.key(1), epochs=args.epochs,
            chunks_per_dispatch=args.megastep, checkpointer=ckpt,
            checkpoint_every=1, start_megastep=start,
            on_megastep=on_chunk, rollback=rollback, recorder=rec,
        )
        ckpt.close()
        if args.obs_dir and rec is not None:
            rec.close()
        np.savez(args.out, weights=weights(store))
        meta.update(finished=True,
                    skipped=sorted(rollback.skipped) if rollback else [],
                    megastep=args.megastep)
        with open(args.out + ".meta.json", "w", encoding="utf-8") as f:
            json.dump(meta, f)
        print(json.dumps({"event": "demo_done", **meta}), flush=True)
        return 0

    stream = chunks[start:]
    if (args.kill_prefetch_at is not None
            and args.kill_prefetch_at >= start):
        # Die while the background worker assembles this chunk (indices
        # in kill_in_prefetch are relative to the resumed stream).
        stream = chaos.kill_in_prefetch(
            iter(stream), args.kill_prefetch_at - start,
            marker=None if args.always else os.path.join(
                args.ckpt_dir, "prefetch_kill.done"),
        )

    rollback = RollbackPolicy(preset=preset) if preset else None
    tables, ls, _ = trainer.fit_stream(
        tables, ls, stream, jax.random.key(1),
        checkpointer=ckpt, checkpoint_every=1, start_step=start,
        on_chunk=on_chunk, rollback=rollback, recorder=rec,
    )
    ckpt.close()
    if args.obs_dir and rec is not None:
        rec.close()  # run_end + final flush (journal = the trace spine)

    np.savez(args.out, weights=(weights(store) if nf == NF else
                                store.lookup_host("weights",
                                                  np.arange(nf))))
    meta.update(finished=True,
                skipped=sorted(rollback.skipped) if rollback else [],
                tiering_restored=tiering_restored,
                delta_publishes=ckpt.delta_publishes,
                full_publishes=ckpt.full_publishes,
                compactions=ckpt.compactions,
                re_ranks=(trainer.retierer.re_ranks
                          if trainer.retierer is not None else None))
    with open(args.out + ".meta.json", "w", encoding="utf-8") as f:
        json.dump(meta, f)
    print(json.dumps({"event": "demo_done", **meta}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
