"""Deterministic fault injection for resilience tests and the chaos sweep.

Three injector families, composable by the tests (``tests/test_resilience.py``,
``tests/test_checkpoint.py``) and by ``tools/chaos_sweep.py``:

* **poison batches** — NaN/Inf/huge values planted into chosen rows of a
  chunk stream's columns, exercising the on-device step-health guard
  (:class:`fps_tpu.core.resilience.GuardConfig`);
* **snapshot corruption** — truncation and bit flips applied to checkpoint
  files on disk, exercising the integrity-verify + fallback-restore path
  (:mod:`fps_tpu.core.checkpoint`);
* **process death** — SIGKILL helpers generalizing
  ``tests/_kill_resume_worker.py``: die at an epoch boundary, or die
  mid-checkpoint-write leaving a partial ``.tmp.npz`` behind;
* **wedged processes** — stop making progress WITHOUT dying (SIGSTOP the
  whole process, or sleep forever inside a chunk callback): the stall
  class only an external supervisor (``fps_tpu.supervise``) can abort,
  exercised end-to-end by ``tools/chaos_sweep.py``'s ``supervised``
  scenario.

Every injector is deterministic: corruption sites come from a seeded
``np.random.default_rng``, never from wall-clock or os entropy, so a
failing chaos test replays bit-for-bit.
"""

from __future__ import annotations

import os
import signal
import tempfile
from typing import Iterable, Iterator, Mapping, Sequence

import numpy as np

POISON_KINDS = ("nan", "inf", "-inf", "huge")


def _poison_value(kind: str, dtype) -> np.ndarray:
    if kind == "nan":
        v = np.nan
    elif kind == "inf":
        v = np.inf
    elif kind == "-inf":
        v = -np.inf
    elif kind == "huge":
        # Finite but norm-exploded: trips the guard's norm tier, not the
        # non-finite tier.
        v = np.finfo(np.dtype(dtype)).max / 4
    else:
        raise ValueError(f"unknown poison kind {kind!r} ({POISON_KINDS})")
    return np.asarray(v, dtype)


def poison_rows(
    array: np.ndarray, rows: Sequence[int], kind: str = "nan"
) -> np.ndarray:
    """Copy of ``array`` with ``rows`` (indices along axis 0) overwritten
    by the poison value."""
    out = np.array(array, copy=True)
    out[np.asarray(rows, np.int64)] = _poison_value(kind, out.dtype)
    return out


def poison_chunks(
    chunks: Iterable[Mapping[str, np.ndarray]],
    *,
    chunk_index: int,
    column: str,
    kind: str = "nan",
    frac: float = 0.25,
    seed: int = 0,
) -> Iterator[dict]:
    """Wrap a chunk stream, poisoning ``frac`` of ``column``'s entries in
    chunk ``chunk_index`` (deterministic sites from ``seed``). Chunk
    leaves keep their ``(T, B, ...)`` layout; poison lands on a seeded
    choice of flat positions of the column, so both sync and SSP chunk
    shapes work unchanged."""
    rng = np.random.default_rng(seed)
    for i, chunk in enumerate(chunks):
        if i != chunk_index:
            yield dict(chunk)
            continue
        out = dict(chunk)
        col = np.array(out[column], copy=True)
        flat = col.reshape(-1)
        n = max(1, int(frac * flat.size))
        sites = rng.choice(flat.size, size=n, replace=False)
        flat[sites] = _poison_value(kind, flat.dtype)
        out[column] = col
        yield out


# ---------------------------------------------------------------------------
# Snapshot corruption (on-disk).
# ---------------------------------------------------------------------------

def truncate_file(path: str, *, keep_frac: float = 0.5) -> str:
    """Truncate ``path`` to ``keep_frac`` of its size (a torn write /
    partial copy). Returns ``path``."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_frac))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return path


def bitflip_file(
    path: str,
    *,
    nflips: int = 16,
    seed: int = 0,
    lo_frac: float = 0.2,
    hi_frac: float = 0.95,
) -> str:
    """Flip ``nflips`` seeded-random bits of ``path`` within the byte
    window ``[lo_frac, hi_frac)`` of the file (the payload region of an
    ``.npz`` — away from the leading zip local header so the corruption
    models silent bit rot in array data, not an unopenable file; the
    integrity layer must catch both either way). Returns ``path``."""
    size = os.path.getsize(path)
    lo, hi = int(size * lo_frac), max(int(size * hi_frac), int(size * lo_frac) + 1)
    rng = np.random.default_rng(seed)
    with open(path, "r+b") as f:
        for _ in range(nflips):
            off = int(rng.integers(lo, hi))
            bit = int(rng.integers(0, 8))
            f.seek(off)
            b = f.read(1)[0]
            f.seek(off)
            f.write(bytes([b ^ (1 << bit)]))
    return path


def snapshot_paths(ckpt_dir: str) -> list[str]:
    """Snapshot files under ``ckpt_dir``, oldest→newest — the naming
    contract comes from the checkpoint layer itself (lazy import: the
    other injectors stay importable without pulling jax in)."""
    from fps_tpu.core.checkpoint import SNAPSHOT_RE

    out = []
    for f in os.listdir(ckpt_dir):
        if SNAPSHOT_RE.fullmatch(f):
            out.append(os.path.join(ckpt_dir, f))
    return sorted(out)


def corrupt_latest_snapshot(
    ckpt_dir: str, mode: str = "truncate", **kwargs
) -> str:
    """Corrupt the NEWEST snapshot under ``ckpt_dir`` (``mode`` is
    ``"truncate"`` or ``"bitflip"``; kwargs forward to the injector).
    Returns the corrupted path."""
    paths = snapshot_paths(ckpt_dir)
    if not paths:
        raise FileNotFoundError(f"no snapshots under {ckpt_dir}")
    target = paths[-1]
    if mode == "truncate":
        return truncate_file(target, **kwargs)
    if mode == "bitflip":
        return bitflip_file(target, **kwargs)
    raise ValueError(f"unknown corruption mode {mode!r}")


# ---------------------------------------------------------------------------
# Process-death injectors (subprocess scenarios).
# ---------------------------------------------------------------------------

def sigkill_self() -> None:
    """Die NOW, with no atexit/flush — the crash the kill-resume contract
    is about."""
    os.kill(os.getpid(), signal.SIGKILL)


def kill_at_epoch(epoch: int):
    """``on_epoch``/``on_chunk`` callback that SIGKILLs the process after
    index ``epoch`` finishes training but before its checkpoint lands."""

    def cb(e, _metrics):
        if e == epoch:
            sigkill_self()

    return cb


def kill_at_chunk(index: int, *, marker: str | None = None,
                  before=None):
    """``on_chunk``/``on_epoch`` callback that SIGKILLs (no flush) after
    chunk ``index`` finishes training but before its checkpoint lands —
    the marker-gated, supervisor-friendly variant of
    :func:`kill_at_epoch` (once-only across restarted attempts, like
    :func:`wedge_at_chunk`). ``before`` runs just before dying (e.g. an
    async checkpointer ``flush()`` when the scenario's lost-work bound
    requires prior snapshots durable)."""

    def cb(i, _metrics):
        if i != index:
            return
        if marker is not None:
            if os.path.exists(marker):
                return
            open(marker, "w").close()
        if before is not None:
            before()
        sigkill_self()

    return cb


def sigstop_self() -> None:
    """Freeze NOW: every thread stops, the heartbeat stops, collectives
    involving this process stall forever — but the process does NOT die,
    and SIGTERM merely queues until a SIGCONT that never comes. The wedge
    only the supervisor's SIGKILL escalation can clear."""
    os.kill(os.getpid(), signal.SIGSTOP)


def sleep_forever() -> None:
    """Wedge the calling thread without stopping the process: the Python
    loop stops driving dispatches while signal handlers stay live —
    the 'quietly hung host loop' variant of a stall (a SIGTERM would
    still kill this one; SIGSTOP models the harder case)."""
    import time

    while True:
        time.sleep(3600)


def wedge_at_chunk(index: int, mode: str = "sigstop", *,
                   marker: str | None = None):
    """``on_chunk``/``on_epoch`` callback that wedges the process after
    chunk ``index`` finishes training but BEFORE its checkpoint lands —
    the supervisor-scenario analog of :func:`kill_at_epoch`.

    ``mode``: ``"sigstop"`` (freeze the whole process) or ``"sleep"``
    (wedge the host loop). ``marker``: a file path making the wedge
    once-only — the callback touches it before wedging, and a restarted
    attempt that finds it proceeds cleanly (deterministic wedge-once, no
    wall-clock or entropy involved).
    """
    if mode not in ("sigstop", "sleep"):
        raise ValueError(f"unknown wedge mode {mode!r}")

    def cb(i, _metrics):
        if i != index:
            return
        if marker is not None:
            if os.path.exists(marker):
                return
            open(marker, "w").close()
        sigstop_self() if mode == "sigstop" else sleep_forever()

    return cb


def kill_in_prefetch(chunks: Iterable, index: int, *,
                     marker: str | None = None) -> Iterator:
    """Re-yield ``chunks`` but die (SIGKILL, no flush) right before chunk
    ``index`` is handed to the consumer — i.e. while the OVERLAPPED host
    pipeline's worker thread (:mod:`fps_tpu.core.prefetch`) is mid-
    assembly, typically several chunks ahead of the chunk the driver is
    dispatching. The death-between-chunk-boundaries case the intra-chunk
    heartbeat phases attribute and the supervisor must resume through.

    ``marker``: a file path making the kill once-only across supervised
    attempts (touched before dying — durable enough for a process kill,
    where the page cache survives; NOT a power-loss guarantee)."""
    for i, c in enumerate(chunks):
        if i == index:
            if marker is None or not os.path.exists(marker):
                if marker is not None:
                    open(marker, "w").close()
                sigkill_self()
        yield c


def partial_write_then_kill(directory: str, nbytes: int = 4096) -> None:
    """Simulate dying MID-checkpoint-write: leave a partial ``.tmp.npz``
    (zip magic + junk) in ``directory`` — exactly what a crashed
    ``_atomic_savez`` leaves before its ``os.replace`` — then SIGKILL.

    The recovery contract under test: a fresh ``Checkpointer`` sweeps the
    stale tmp file and restore falls back to the newest intact snapshot.
    """
    fd, _tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.npz")
    try:
        os.write(fd, b"PK\x03\x04" + b"\xde\xad" * (max(nbytes - 4, 0) // 2))
        os.fsync(fd)
    finally:
        os.close(fd)
    sigkill_self()
