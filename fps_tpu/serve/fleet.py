"""A step-fenced fleet of ReadServers over one snapshot directory.

PR 7 opened the read plane with ONE ``ReadServer`` — a throughput
ceiling and a single point of failure for the serving half Parameter Box
(PAPERS.md) treats as the product. This module grows it into N readers
with **consistent step fencing**: the write side's fencing (PR 11's pod
epochs) keeps stale trainers from publishing; this is the READ side's
twin, keeping stale readers from answering.

The fence protocol (all files live under ``<ckpt_dir>/fleet/``, shared
by every reader over the same filesystem the snapshots ride):

* each reader continuously verifies candidates with its own
  :class:`~fps_tpu.serve.watcher.SnapshotWatcher` and records the newest
  step it could serve in its READINESS slot (``ready_<id>.json``,
  atomic-rename JSON like everything here);
* any reader may ADVANCE the shared fence (``serve_fence.json``) to the
  highest step at least ``quorum`` readers are ready on — forward-
  monotone within a fencing epoch, last-writer-wins races are harmless
  because every write is a step at/behind quorum readiness and readers
  clamp to the max ``(epoch, step)`` they have ever observed;
* readers swap their servers to EXACTLY the fence step — never ahead of
  it (a reader ahead would supersede every fence-step answer in flight),
  never behind it (a reader killed and restarted mid-swap re-reads the
  fence at boot and refuses to serve anything older — the
  restart-never-regresses contract the chaos scenario pins);
* BACKWARD swaps stay coordinated: when the trainer quarantines the
  fence step (``*.corrupt``), the reader that observes it rolls the
  fence back to the newest survivor with an incremented fence EPOCH —
  readers accept a lower step only under a higher epoch, so a delayed
  stale fence write can never drag the fleet backward by accident.

Freshness rides the same machinery as the single-reader plane:
``serve.fence_step`` is the fleet-wide published step; delta publishes
hot-swap INCREMENTALLY (``ServableSnapshot.with_delta``: touched rows
overlaid on the still-mapped base); and each reader admits a WARM-ROW
cache from the hot-tier frequency ranking (the adaptive tier's sidecar
``hot::`` ids, or any explicit id set) so hot lookups come from resident
buffers instead of faulting mapped pages.

jax-free (stdlib + numpy), like the rest of ``fps_tpu.serve``.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time

import numpy as np

from fps_tpu.core import retry as _retry
from fps_tpu.core import snapshot_format as fmt
from fps_tpu.obs.trace import Tracer
from fps_tpu.serve.snapshot import ServableSnapshot, SnapshotRejected
from fps_tpu.serve.server import ReadServer
from fps_tpu.serve.watcher import SnapshotWatcher, _emit_event, \
    _emit_metric

__all__ = ["StepFence", "FleetReader", "ServingFleet", "ReadAutoscaler",
           "tiering_hot_ids", "scan_heartbeats", "liveness_check"]

FLEET_DIR = "fleet"
FENCE_NAME = "serve_fence.json"

# Liveness defaults: beacons ride the fleet dir (atomic-rename JSON like
# everything here) at HEARTBEAT_INTERVAL_S; a reader whose newest beacon
# is older than DEFAULT_LIVENESS_TIMEOUT_S is classified reader_wedged —
# an INCIDENT the supervisor restarts, never a silent 0 q/s (BENCH_r14).
HEARTBEAT_INTERVAL_S = 1.0
DEFAULT_LIVENESS_TIMEOUT_S = 5.0


def _atomic_write_json(path: str, obj: dict) -> None:
    # Deliberately a local twin of the helpers in
    # supervise/supervisor.py and supervise/pod.py: those modules are
    # loaded BY FILE PATH from tools/supervise.py (zero package
    # imports, by contract), so a shared package-level helper cannot
    # serve all three without breaking that load mode.
    _retry.fault_check("write", path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.json")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(obj, f)
            f.flush()
            _retry.fault_check("fsync", path)
            os.fsync(f.fileno())
        _retry.fault_check("replace", path)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _read_json(path: str) -> dict | None:
    try:
        path = _retry.read_path(path)  # stale read-after-rename seam
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class StepFence:
    """The shared fleet fence + this reader's readiness slot.

    The fence value is a ``(epoch, step)`` pair ordered
    lexicographically: higher epoch wins outright (a coordinated
    rollback), otherwise higher step wins (normal forward motion). Each
    reader clamps to the maximum pair it has ever OBSERVED, so a
    last-writer-wins race between two advancing readers (both writing
    quorum-backed values) can never move any observer backward within an
    epoch.
    """

    def __init__(self, ckpt_dir: str, reader_id: str):
        self.dir = os.path.join(ckpt_dir, FLEET_DIR)
        self.reader_id = str(reader_id)
        os.makedirs(self.dir, exist_ok=True)
        self._seen = (0, -1)  # max (epoch, step) ever observed
        self._last_ready: int | None = None  # skip unchanged rewrites
        # Transient fence-I/O failures (storage brownout): every write
        # here is re-attempted by the next poll tick anyway, so a
        # failed one is counted and SKIPPED — degraded liveness, never
        # a crashed poller or a split-brain (reads clamp to the max
        # observed pair regardless of what lands on disk when).
        self.io_errors = 0

    @property
    def fence_path(self) -> str:
        return os.path.join(self.dir, FENCE_NAME)

    def _ready_path(self, reader_id: str) -> str:
        return os.path.join(self.dir, f"ready_{reader_id}.json")

    # -- observation -------------------------------------------------------

    def read(self) -> tuple[int, int] | None:
        """Current effective fence as ``(epoch, step)`` (clamped to the
        max ever observed), or None before the first advance. A FILE
        regressed below this reader's max (a racing advance's
        last-writer-wins clobbering a rollback's epoch bump) is
        REPAIRED back up — anti-entropy, so peers that never observed
        the higher pair converge instead of serving past it."""
        rec = _read_json(self.fence_path)
        pair = None
        if rec is not None:
            try:
                pair = (int(rec["epoch"]), int(rec["step"]))
            except (KeyError, TypeError, ValueError):
                pair = None
        if pair is not None and pair > self._seen:
            self._seen = pair
        elif (pair is not None and pair < self._seen
                and self._seen[1] >= 0):
            try:
                _atomic_write_json(self.fence_path,
                                   {"epoch": self._seen[0],
                                    "step": self._seen[1],
                                    "by": self.reader_id,
                                    "repair": True})
            except OSError:
                self.io_errors += 1  # anti-entropy retried next read
        return self._seen if self._seen[1] >= 0 else None

    # -- participation -----------------------------------------------------

    def ready(self, step: int) -> None:
        """Record the newest step THIS reader has verified locally.
        Idempotent per step: an unchanged readiness is not rewritten —
        the poll loop calls this every tick, and ~20 fsync'd renames per
        second per reader against a (possibly networked) shared
        filesystem would be pure churn."""
        if self._last_ready == int(step):
            return
        try:
            _atomic_write_json(self._ready_path(self.reader_id),
                               {"reader": self.reader_id,
                                "step": int(step), "t": time.time()})
        except OSError:
            self.io_errors += 1
            return  # _last_ready stays unset: retried next tick
        self._last_ready = int(step)

    def ready_steps(self) -> dict[str, int]:
        out: dict[str, int] = {}
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return out
        for f in names:
            if not (f.startswith("ready_") and f.endswith(".json")):
                continue
            rec = _read_json(os.path.join(self.dir, f))
            if rec is None:
                continue
            try:
                out[str(rec["reader"])] = int(rec["step"])
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def advance(self, quorum: int, *, max_step: int | None = None
                ) -> tuple[int, int] | None:
        """Advance the fence to the highest step at least ``quorum``
        readers are ready on (forward-monotone within the current
        epoch); returns the effective fence either way. ``max_step``
        caps the target at the ADVANCING reader's own verified step —
        after a coordinated rollback, peers' not-yet-refreshed readiness
        slots (still naming the quarantined step) must not be able to
        drag the fence forward past what this reader just verified."""
        cur = self.read()
        steps = sorted(self.ready_steps().values(), reverse=True)
        if len(steps) >= max(1, quorum):
            target = steps[max(0, quorum - 1)]
            if max_step is not None:
                target = min(target, int(max_step))
            epoch = cur[0] if cur is not None else 0
            if cur is None or target > cur[1]:
                try:
                    _atomic_write_json(self.fence_path,
                                       {"epoch": int(epoch),
                                        "step": int(target),
                                        "by": self.reader_id})
                    self._seen = max(self._seen, (epoch, target))
                except OSError:
                    self.io_errors += 1  # fence unchanged; next tick
        return self.read()

    def rollback(self, step: int) -> tuple[int, int]:
        """Coordinated BACKWARD fence move (served step quarantined):
        bump the epoch so every reader accepts the lower step as a
        deliberate rollback, never as a stale write."""
        cur = self.read()
        epoch = (cur[0] if cur is not None else 0) + 1
        try:
            _atomic_write_json(self.fence_path,
                               {"epoch": int(epoch), "step": int(step),
                                "by": self.reader_id, "rollback": True})
        except OSError:
            # Count and adopt the bumped pair LOCALLY anyway: this
            # reader must stop serving the dead step now; the on-disk
            # fence converges via read()'s anti-entropy repair (the
            # rollback is re-asserted every poll regardless).
            self.io_errors += 1
        self._seen = (epoch, int(step))
        return self._seen


def tiering_hot_ids(ckpt_dir: str, table: str | None = None) -> dict:
    """Warm-cache admission from the adaptive tier's frequency ranking:
    the newest ``tiering-*.npz`` sidecar's ``hot::<table>`` id arrays
    (``fps_tpu.tiering.Retierer`` writes them beside the checkpoints).
    Returns ``{table: ids}`` (optionally filtered to one table); empty
    when no sidecar exists — warm caching simply stays off."""
    import re

    sidecar_re = re.compile(r"tiering-(\d+)\.npz")
    newest, newest_step = None, -1
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return {}
    for f in names:
        m = sidecar_re.fullmatch(f)
        if m and int(m.group(1)) > newest_step:
            newest, newest_step = os.path.join(ckpt_dir, f), int(m.group(1))
    if newest is None:
        return {}
    out: dict[str, np.ndarray] = {}
    try:
        with np.load(newest) as z:
            for k in z.files:
                if k.startswith("hot::"):
                    name = k[len("hot::"):]
                    if table is None or name == table:
                        out[name] = np.asarray(z[k], np.int64)
    except (OSError, *fmt.IO_ERRORS):
        return {}
    return out


class FleetReader:
    """One member of the serving fleet: a ReadServer whose hot-swaps are
    gated on the shared step fence.

    ``poll()`` drives everything: candidate discovery/verification (the
    embedded :class:`SnapshotWatcher` — including delta chains and
    quarantine tracking), readiness publication, fence advancement, and
    the actual server swap to the fence step. Construction re-reads the
    fence FIRST: a reader restarted mid-swap never answers a step older
    than the fleet's published fence.

    ``shadow=True`` gates the reader on the tenant's shadow-serving
    promotion record (:class:`~fps_tpu.serve.shadow.ShadowGate`):
    readiness and fence advancement are capped at the newest APPROVED
    step, so a publication the scorer held (or has not judged yet) is
    invisible to the fleet — it keeps serving the old approved step.
    Lost freshness, never wrong answers (docs/STALENESS.md).
    """

    def __init__(self, ckpt_dir: str, reader_id: str, *, quorum: int = 1,
                 journal: str | None = None, recorder=None,
                 warm_from=None, verify: bool = True,
                 heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
                 shadow: bool = False):
        self.ckpt_dir = ckpt_dir
        self.reader_id = str(reader_id)
        self.quorum = int(quorum)
        if shadow:
            from fps_tpu.serve.shadow import ShadowGate
            self.shadow_gate = ShadowGate(ckpt_dir)
        else:
            self.shadow_gate = None
        self.recorder = recorder
        self.verify = verify
        # warm_from: None | {table: ids} | "tiering" (sidecar ranking).
        self.warm_from = warm_from
        self.server = ReadServer(recorder=recorder)
        self.fence = StepFence(ckpt_dir, reader_id)
        self._candidate: ServableSnapshot | None = None
        self._rollback_due = False
        self.fence_swaps = 0
        self.poll_errors = 0  # transient poll failures (loop survives)
        self.served_steps: list[int] = []  # trail for the chaos harness
        # Liveness beacon state: throttled (one fsync'd rename per
        # interval, not per poll tick — the same churn argument as
        # StepFence.ready), best-effort (a storage fault skips one
        # beacon, counted, and the next interval retries — a brownout
        # must not impersonate a wedged reader any longer than it
        # actually lasts).
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._last_hb = 0.0
        self.hb_errors = 0
        self.polls = 0
        self.born = time.time()  # boot-grace anchor for liveness
        self.watcher = SnapshotWatcher(
            ckpt_dir, journal=journal, recorder=recorder,
            on_swap=self._on_candidate, verify=verify)
        # Boot protocol: observe the existing fence before serving
        # anything — the restart-never-regresses half of the contract.
        self.fence.read()

    # -- candidate tracking (watcher callback) -----------------------------

    def _on_candidate(self, snap: ServableSnapshot, direction: str):
        self._candidate = snap
        if direction == "backward":
            # The watcher only ever swaps backward past a quarantine /
            # vanish of the served candidate: propose a coordinated
            # fence rollback instead of silently diverging.
            self._rollback_due = True

    def _fence_step_dead(self, step: int) -> bool:
        """True when the fence names a step this reader can PROVE is no
        longer servable: quarantined — its own ``*.corrupt`` marker or
        one on a chain link. Persistent on-disk evidence only: "absent
        from my last scan" is NOT proof (a reader whose scan is one
        poll stale would spuriously epoch-bump a fence its peers just
        legitimately advanced — a backward fleet swap off a live step).
        A step swept with no marker at all simply holds the fence until
        newer publications advance it: lost liveness, never
        split-brain."""
        w = self.watcher
        return step in w._quarantined or w._chain_quarantined(step)

    # -- the poll ----------------------------------------------------------

    def poll(self) -> int | None:
        """One pass: verify candidates, publish readiness, advance (or
        roll back) the fence, swap the server to the fence step. Returns
        the served step (None while nothing servable). Transient
        filesystem errors degrade (served state unchanged, counted in
        ``poll_errors`` / ``storage.poll_errors{plane=fleet}``) —
        a storage brownout must never freeze or crash a reader."""
        self.polls += 1
        try:
            served = self._poll_once()
        except OSError as e:
            self.poll_errors += 1
            _emit_metric(self.recorder, "inc", "storage.poll_errors", 1,
                         plane="fleet")
            logging.getLogger("fps_tpu.serve.fleet").warning(
                "fleet reader %s poll degraded (serving last-good): %r",
                self.reader_id, e)
            snap = self.server._snap
            served = None if snap is None else snap.step
        # Beacon AFTER the poll body, degraded or not: liveness means
        # "this reader's loop is turning", not "storage is healthy" —
        # a reader surviving a brownout is alive, a SIGSTOPped or
        # deadlocked one is not, and only the latter must trip the
        # reader_wedged classification.
        self._beat(served)
        return served

    # -- liveness beacon ----------------------------------------------------

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.fence.dir,
                            f"heartbeat_{self.reader_id}.json")

    def _beat(self, served) -> None:
        now = time.time()
        if now - self._last_hb < self.heartbeat_interval_s:
            return
        beat = {"reader": self.reader_id, "t": now,
                "step": None if served is None else int(served),
                "requests": int(self.server.requests),
                "polls": int(self.polls)}
        try:
            _atomic_write_json(self.heartbeat_path, beat)
        except OSError:
            self.hb_errors += 1  # best-effort: next interval retries
            return
        self._last_hb = now
        # The beacon rides the obs journal too, so a journal-only
        # post-mortem (obs_report) can reconstruct per-reader liveness
        # without the fleet dir.
        _emit_event(self.recorder, "serve.reader_heartbeat",
                    reader=self.reader_id, step=beat["step"],
                    requests=beat["requests"])

    def _poll_once(self) -> int | None:
        self.watcher.poll()
        cand = self._candidate
        # Shadow gating: readiness AND fence advancement are capped at
        # the approved step. While nothing is approved a gated reader
        # neither declares readiness nor advances — stale readiness
        # slots (a gate enabled over an existing fleet dir) must not be
        # able to drag the fence past the scorer.
        ready = None if cand is None else cand.step
        advance_cap = ready
        if self.shadow_gate is not None:
            approved = self.shadow_gate.approved_step()
            if approved is None:
                ready = advance_cap = None
            else:
                advance_cap = (approved if ready is None
                               else min(ready, approved))
                ready = None if ready is None else min(ready, approved)
        if ready is not None:
            self.fence.ready(ready)
        cur = self.fence.read()
        # Coordinated rollback, EVIDENCE-based and re-assertable: when
        # the fence names a step this reader's watcher has proven
        # quarantined/unresolvable (persistent on-disk evidence — not a
        # one-shot flag), bump the epoch down to the surviving
        # candidate. Re-checked every poll, so a racing advance that
        # clobbers the rollback write gets rolled back again until the
        # fleet converges.
        if (cand is not None and cur is not None
                and cand.step < cur[1]
                and (self._rollback_due
                     or self._fence_step_dead(cur[1]))):
            cur = self.fence.rollback(cand.step)
        self._rollback_due = False
        if self.shadow_gate is None or advance_cap is not None:
            cur = self.fence.advance(self.quorum, max_step=advance_cap)
        self._apply_fence(cur)
        snap = self.server._snap
        return None if snap is None else snap.step

    def _apply_fence(self, fence: tuple[int, int] | None) -> None:
        if fence is None:
            return
        _epoch, step = fence
        # Gauge every poll, not just on swaps: the fleet fence-lag SLO
        # (obs_report --fleet) compares the LAST sample per window
        # against the newest published step — a fence STALLED behind
        # failing readiness writes must keep reporting its (stale)
        # step, or the lag rollup goes blind in exactly the windows
        # the SLO exists for.
        _emit_metric(self.recorder, "set", "serve.fence_step",
                     float(step))
        snap = self.server._snap
        if snap is not None and snap.step == step:
            return
        cand = self._candidate
        nxt = None
        if cand is not None and cand.step == step:
            nxt = cand
        else:
            # The fence names a step this reader hasn't verified as its
            # newest candidate (it is behind, ahead, or freshly booted):
            # open that exact step from the shared dir — chains welcome.
            try:
                nxt = ServableSnapshot.open_chain(self.ckpt_dir, step,
                                                  verify=self.verify)
            except (FileNotFoundError, SnapshotRejected):
                if snap is not None and snap.step > step:
                    # The fence moved BACKWARD (coordinated quarantine
                    # rollback) and the lower step isn't openable yet:
                    # answering from the old higher step would serve the
                    # quarantined state the fence just rolled past.
                    # Refuse (NoSnapshotError to clients) until a poll
                    # can open the fence step — behind is lag, ahead is
                    # split-brain.
                    self.server.swap_to(None)
                return  # otherwise hold the current (older) snapshot
        if self.warm_from is not None:
            ids = (tiering_hot_ids(self.ckpt_dir)
                   if self.warm_from == "tiering" else self.warm_from)
            if ids:
                nxt = nxt.warmed(ids)
        self.server.swap_to(nxt)
        self.fence_swaps += 1
        self.served_steps.append(int(step))

    def stats(self) -> dict:
        snap = self.server._snap
        return {
            "reader": self.reader_id,
            "step": None if snap is None else snap.step,
            "fence": self.fence.read(),
            "fence_swaps": self.fence_swaps,
            "chain_len": None if snap is None else snap.chain_len,
            "warm_rows": 0 if snap is None else snap.warm_rows,
            **self.server.stats(),
        }


class ServingFleet:
    """N fence-coordinated readers over one snapshot dir (the bench and
    chaos harness topology; production runs one FleetReader per serving
    process over a shared filesystem).

    ``quorum`` defaults to a majority of the fleet — the fence advances
    once most readers verified a step, and laggards converge to it.
    Membership is DYNAMIC: :meth:`add_reader` / :meth:`remove_reader`
    grow and shrink a running fleet (the autoscaler's levers); a
    default (majority) quorum re-derives on every membership change,
    an explicit quorum stays pinned until :meth:`set_quorum`."""

    def __init__(self, ckpt_dir: str, n_readers: int = 3, *,
                 quorum: int | None = None, journal: str | None = None,
                 recorder=None, warm_from=None, verify: bool = True,
                 shadow: bool = False):
        if n_readers < 1:
            raise ValueError(f"n_readers must be >= 1, got {n_readers}")
        self.ckpt_dir = ckpt_dir
        self.recorder = recorder
        # Reader construction kwargs, kept so add_reader() builds
        # members identical to the ctor's.
        self._reader_kw = {"journal": journal, "recorder": recorder,
                           "warm_from": warm_from, "verify": verify,
                           "shadow": shadow}
        self._auto_quorum = quorum is None
        self.quorum = (n_readers // 2 + 1) if quorum is None else quorum
        self.readers = [
            FleetReader(ckpt_dir, f"r{i}", quorum=self.quorum,
                        **self._reader_kw)
            for i in range(n_readers)
        ]
        self._next_id = n_readers
        self._retired: set[str] = set()
        self._admin_lock = threading.RLock()
        self._threads: list[threading.Thread] = []
        self._started = False
        self._stop = threading.Event()
        self._interval_s = 0.05

    def poll(self) -> None:
        for r in list(self.readers):
            r.poll()

    def start(self, interval_s: float = 0.05) -> None:
        """One polling thread per reader (the fleet topology in one
        process). ``stop()`` joins them."""
        with self._admin_lock:
            self._stop.clear()
            self._started = True
            self._interval_s = interval_s
            self._threads = [
                threading.Thread(target=self._loop, args=(r,),
                                 daemon=True,
                                 name=f"fps-fleet-{r.reader_id}")
                for r in self.readers
            ]
            for t in self._threads:
                t.start()

    def _loop(self, reader) -> None:
        # A method (not a start() closure) so check_liveness can spawn
        # a REPLACEMENT thread for a wedged reader through the same
        # code path.
        log = logging.getLogger("fps_tpu.serve.fleet")
        while not (self._stop.is_set()
                   or reader.reader_id in self._retired):
            try:
                reader.poll()
            except Exception:  # noqa: BLE001 — the loop must live
                # A transient shared-filesystem error (ENOSPC/NFS
                # hiccup in the fence/readiness writes) must not
                # silently kill the poller and freeze this reader on
                # a stale snapshot while its peers move on — log,
                # count, retry next tick.
                reader.poll_errors += 1
                log.exception("fleet reader %s poll failed "
                              "(retrying)", reader.reader_id)
            self._stop.wait(self._interval_s)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._admin_lock:
            threads, self._threads = self._threads, []
            self._started = False
        for t in threads:
            t.join(timeout=timeout)

    # -- dynamic membership (the autoscaler's levers) -----------------------

    def set_quorum(self, quorum: int) -> None:
        """Pin an explicit fence quorum on every current member (future
        members inherit it). Auto-majority derivation stops."""
        if quorum < 1:
            raise ValueError(f"quorum must be >= 1, got {quorum}")
        with self._admin_lock:
            self._auto_quorum = False
            self.quorum = int(quorum)
            for r in self.readers:
                r.quorum = self.quorum

    def _requorum(self) -> None:
        # Default quorum follows the membership: a majority of the
        # CURRENT fleet. An explicitly pinned quorum is clamped to the
        # fleet size so a shrink can never make the fence unreachable.
        if self._auto_quorum:
            self.quorum = len(self.readers) // 2 + 1
        else:
            self.quorum = min(self.quorum, len(self.readers))
        for r in self.readers:
            r.quorum = self.quorum

    def add_reader(self, reader_id: str | None = None) -> FleetReader:
        """Spawn one more fence-coordinated reader (and its polling
        thread, when the fleet is running). Its boot protocol re-reads
        the shared fence first, so a scale-up never regresses the
        served step."""
        with self._admin_lock:
            rid = (f"r{self._next_id}" if reader_id is None
                   else str(reader_id))
            self._next_id += 1
            self._retired.discard(rid)
            reader = FleetReader(self.ckpt_dir, rid, quorum=self.quorum,
                                 **self._reader_kw)
            self.readers.append(reader)
            self._requorum()
            if self._started:
                t = threading.Thread(
                    target=self._loop, args=(reader,), daemon=True,
                    name=f"fps-fleet-{reader.reader_id}")
                self._threads.append(t)
                t.start()
            _emit_event(self.recorder, "reader_added", reader=rid,
                        fleet_size=len(self.readers),
                        quorum=self.quorum)
            return reader

    def remove_reader(self, reader_id: str,
                      timeout: float = 5.0) -> bool:
        """Retire one reader: stop its polling thread, drop it from the
        fleet, and delete its readiness/heartbeat slots so the fence
        quorum and the liveness scan stop counting a ghost. The LAST
        reader is never removable — an empty fleet serves nothing."""
        with self._admin_lock:
            idx = next((i for i, r in enumerate(self.readers)
                        if r.reader_id == reader_id), None)
            if idx is None or len(self.readers) <= 1:
                return False
            reader = self.readers.pop(idx)
            self._retired.add(reader.reader_id)
            thread = self._threads.pop(idx) if self._threads else None
            self._requorum()
        if thread is not None:
            thread.join(timeout=timeout)
        # Ghost-slot cleanup is best-effort: a storage hiccup leaves a
        # stale slot the next liveness scan flags — loud, not wrong.
        for path in (reader.fence._ready_path(reader.reader_id),
                     reader.heartbeat_path):
            try:
                os.remove(path)
            except OSError:
                pass
        _emit_event(self.recorder, "reader_removed",
                    reader=reader.reader_id,
                    fleet_size=len(self.readers), quorum=self.quorum)
        return True

    def stats(self) -> list[dict]:
        return [r.stats() for r in self.readers]

    def check_liveness(self, *,
                       timeout_s: float = DEFAULT_LIVENESS_TIMEOUT_S,
                       recorder=None, now=None) -> dict:
        """One liveness pass over this fleet's beacons:
        ``{"ages": {reader: age_s}, "wedged": [...], "restarted":
        [...]}``. Wedged readers whose polling THREAD has died are
        restarted in place (a replacement thread over the same
        FleetReader — its boot protocol re-reads the fence, so the
        restart never regresses). A thread that is still alive but
        silent (stuck in a blocked syscall) cannot be safely doubled
        up in-process: it is reported as the ``reader_wedged``
        incident and left to the process supervisor, exactly like a
        SIGSTOPped reader process."""
        ckpt_dir = self.readers[0].ckpt_dir
        rec = recorder if recorder is not None else (
            self.readers[0].recorder)
        report = liveness_check(
            ckpt_dir, timeout_s=timeout_s, recorder=rec, now=now,
            expected=[r.reader_id for r in self.readers])
        # Boot grace: a reader added moments ago (the autoscaler's
        # scale-up) has not had a beacon interval yet — classifying it
        # wedged would make every scale-up instantly "fail". Younger
        # than the timeout and beaconless is booting, not wedged.
        wall = time.time() if now is None else now
        born = {r.reader_id: r.born for r in self.readers}
        report["wedged"] = [
            rid for rid in report["wedged"]
            if not (report["ages"].get(rid) is None
                    and wall - born.get(rid, 0.0) < timeout_s)]
        restarted = []
        with self._admin_lock:
            if self._threads and report["wedged"]:
                by_id = {r.reader_id: i
                         for i, r in enumerate(self.readers)}
                for reader_id in report["wedged"]:
                    i = by_id.get(reader_id)
                    if i is None or self._threads[i].is_alive():
                        continue
                    reader = self.readers[i]
                    t = threading.Thread(
                        target=self._loop, args=(reader,), daemon=True,
                        name=f"fps-fleet-{reader.reader_id}")
                    self._threads[i] = t
                    t.start()
                    restarted.append(reader_id)
                    _emit_event(rec, "reader_restarted",
                                reader=reader_id)
        report["restarted"] = restarted
        return report


class ReadAutoscaler:
    """Closed-loop sizing for a :class:`ServingFleet`, keyed to the two
    signals that actually mean "capacity" on the read plane:

    * **latency-SLO burn** — the worst per-reader p99 over the retained
      request window against ``latency_slo_s``. Burning latency while
      the fence is FRESH means the readers are compute-bound: spawn one
      more (up to ``max_readers``).
    * **fence lag** — newest published step minus the fence step.
      Burning latency while the fence is STALE means the bottleneck is
      publish/verify/quorum, which another reader cannot fix (and whose
      fence votes would slow): hold instead of thrash.

    Wedged readers (liveness beacons gone silent) are handled first and
    exempt from the cooldown: dead polling threads are restarted in
    place by :meth:`ServingFleet.check_liveness`; a thread that is
    alive-but-silent is REPLACED — a fresh reader joins (re-reading the
    fence at boot, so no regression), then the wedged one is retired so
    quorum stops waiting on a ghost.

    Every :meth:`evaluate` is journaled as a trace SPAN (the same
    causal-tree machinery as pod restart decisions —
    ``fps_tpu.obs.trace``) with the decision and its evidence as
    attributes, plus an ``autoscale_decision`` event and the
    ``serve.fleet_size`` / ``serve.autoscale_actions`` metrics; the
    in-memory :attr:`decisions` trail serves tests and the bench."""

    def __init__(self, fleet: ServingFleet, *, min_readers: int = 1,
                 max_readers: int = 8, latency_slo_s: float = 0.050,
                 fence_lag_slo_steps: float = 8.0,
                 scale_down_fraction: float = 0.25,
                 cooldown_s: float = 5.0,
                 liveness_timeout_s: float = DEFAULT_LIVENESS_TIMEOUT_S,
                 recorder=None):
        if not 1 <= min_readers <= max_readers:
            raise ValueError(
                f"need 1 <= min_readers <= max_readers, got "
                f"[{min_readers}, {max_readers}]")
        self.fleet = fleet
        self.min_readers = int(min_readers)
        self.max_readers = int(max_readers)
        self.latency_slo_s = float(latency_slo_s)
        self.fence_lag_slo_steps = float(fence_lag_slo_steps)
        self.scale_down_fraction = float(scale_down_fraction)
        self.cooldown_s = float(cooldown_s)
        self.liveness_timeout_s = float(liveness_timeout_s)
        self.recorder = (recorder if recorder is not None
                         else fleet.recorder)
        self._tracer = Tracer(self.recorder)
        self._last_scale_mono: float | None = None
        self.decisions: list[dict] = []

    # -- signals ------------------------------------------------------------

    def worst_p99_s(self) -> float | None:
        """Worst per-reader p99 latency over the retained window (None
        until any reader has served requests)."""
        p99s = []
        for r in list(self.fleet.readers):
            lat = r.server.latency_s()
            if lat is not None:
                p99s.append(lat["p99"])
        return max(p99s) if p99s else None

    def fence_lag_steps(self, newest_step: int | None = None
                        ) -> float | None:
        """Newest published step minus the effective fence step.
        ``newest_step`` overrides discovery (the bench/chaos harness
        knows exactly what it published); otherwise the newest
        readiness slot stands in — some reader VERIFIED that step, so
        the fence trailing it is real lag."""
        readers = list(self.fleet.readers)
        if not readers:
            return None
        fence = readers[0].fence.read()
        if newest_step is None:
            steps = readers[0].fence.ready_steps().values()
            newest_step = max(steps, default=None)
        if newest_step is None or fence is None:
            return None
        return float(int(newest_step) - fence[1])

    # -- the control loop body ----------------------------------------------

    def evaluate(self, *, newest_step: int | None = None,
                 now: float | None = None) -> dict:
        """One sizing pass: liveness repair first, then at most ONE
        scale action (cooldown-gated). Returns the decision record
        (also appended to :attr:`decisions` and journaled)."""
        t0 = time.time()
        mono = time.monotonic() if now is None else float(now)
        report = self.fleet.check_liveness(
            timeout_s=self.liveness_timeout_s, recorder=self.recorder)
        replaced = []
        for rid in report["wedged"]:
            if rid in report["restarted"]:
                continue
            # Alive-but-silent thread: replace, never double up on the
            # same FleetReader (check_liveness's contract). Join first,
            # retire after — the fleet never dips below size.
            if len(self.fleet.readers) < self.max_readers + 1:
                fresh = self.fleet.add_reader()
                if self.fleet.remove_reader(rid, timeout=0.5):
                    replaced.append({"wedged": rid,
                                     "replacement": fresh.reader_id})
                    _emit_event(self.recorder, "reader_replaced",
                                wedged=rid,
                                replacement=fresh.reader_id)
        p99 = self.worst_p99_s()
        lag = self.fence_lag_steps(newest_step)
        size = len(self.fleet.readers)
        lag_ok = lag is None or lag <= self.fence_lag_slo_steps
        cooled = (self._last_scale_mono is None
                  or mono - self._last_scale_mono >= self.cooldown_s)
        action, reason, target = "hold", "within slo", None
        if replaced:
            action = "replace"
            reason = f"replaced wedged reader(s): " \
                     f"{[r['wedged'] for r in replaced]}"
        elif (p99 is not None and p99 > self.latency_slo_s
                and not lag_ok):
            reason = (f"latency burn (p99 {p99:.4f}s) but fence lag "
                      f"{lag:.0f} steps over slo — publish-bound, "
                      "another reader won't help")
        elif (p99 is not None and p99 > self.latency_slo_s
                and size < self.max_readers and cooled):
            action, reason = "scale_up", (
                f"p99 {p99:.4f}s over slo {self.latency_slo_s:.4f}s "
                f"with fresh fence")
            target = self.fleet.add_reader().reader_id
            self._last_scale_mono = mono
        elif (p99 is not None and size > self.min_readers and cooled
                and p99 < self.scale_down_fraction * self.latency_slo_s):
            victim = self.fleet.readers[-1].reader_id
            if self.fleet.remove_reader(victim):
                action, reason, target = "scale_down", (
                    f"p99 {p99:.4f}s under "
                    f"{self.scale_down_fraction:.0%} of slo"), victim
                self._last_scale_mono = mono
        decision = {
            "t": t0, "action": action, "reason": reason,
            "target": target, "replaced": replaced,
            "fleet_size": len(self.fleet.readers),
            "quorum": self.fleet.quorum,
            "worst_p99_s": p99, "fence_lag_steps": lag,
            "wedged": report["wedged"],
            "restarted": report["restarted"],
        }
        self.decisions.append(decision)
        # Journal the decision as a causal span + event + gauges: the
        # autoscaler's choices must be post-mortem-able from the obs
        # journal alone, exactly like pod restart decisions.
        self._tracer.emit("autoscale_evaluate", t0, time.time(),
                          action=action, reason=reason, target=target,
                          fleet_size=decision["fleet_size"],
                          worst_p99_s=p99, fence_lag_steps=lag)
        _emit_event(self.recorder, "autoscale_decision", **{
            k: v for k, v in decision.items() if k != "t"})
        _emit_metric(self.recorder, "set", "serve.fleet_size",
                     float(decision["fleet_size"]))
        if action != "hold":
            _emit_metric(self.recorder, "inc",
                         "serve.autoscale_actions", 1, action=action)
        return decision


def scan_heartbeats(ckpt_dir: str, *, now=None) -> dict:
    """Read every ``heartbeat_<id>.json`` beacon under
    ``<ckpt_dir>/fleet/``: ``{reader: {"t", "step", "requests",
    "polls", "age_s"}}``. File-based on purpose — the monitor side
    (supervisor, bench, chaos harness) runs in a DIFFERENT process
    than the readers it is judging, and a SIGSTOPped reader cannot
    lie through a file it can no longer write."""
    now = time.time() if now is None else now
    out: dict[str, dict] = {}
    fleet_dir = os.path.join(ckpt_dir, FLEET_DIR)
    try:
        names = os.listdir(fleet_dir)
    except FileNotFoundError:
        return out
    for f in names:
        if not (f.startswith("heartbeat_") and f.endswith(".json")):
            continue
        rec = _read_json(os.path.join(fleet_dir, f))
        if rec is None:
            continue
        try:
            reader = str(rec["reader"])
            t = float(rec["t"])
        except (KeyError, TypeError, ValueError):
            continue
        out[reader] = {"t": t, "step": rec.get("step"),
                       "requests": rec.get("requests"),
                       "polls": rec.get("polls"),
                       "age_s": max(0.0, now - t)}
    return out


def liveness_check(ckpt_dir: str, *,
                   timeout_s: float = DEFAULT_LIVENESS_TIMEOUT_S,
                   recorder=None, now=None,
                   expected=None) -> dict:
    """Classify fleet liveness from the beacons: a reader whose newest
    beacon is older than ``timeout_s`` — or, with ``expected`` ids
    given, one that never wrote a beacon at all — is WEDGED. Each pass
    gauges ``serve.reader_heartbeat_age_s`` per reader (the staleness
    SLO input) and journals one ``reader_wedged`` incident per wedged
    reader; returns ``{"ages": {reader: age_s}, "wedged": [ids]}``.
    A wedged reader is an INCIDENT the supervisor acts on, never a
    silent zero in a bench average (BENCH_r14)."""
    beats = scan_heartbeats(ckpt_dir, now=now)
    ages = {r: b["age_s"] for r, b in beats.items()}
    wedged = sorted(r for r, age in ages.items() if age > timeout_s)
    for missing in sorted(set(expected or ()) - set(ages)):
        ages[missing] = None
        wedged.append(missing)
    for reader, age in sorted(ages.items()):
        if age is not None:
            _emit_metric(recorder, "set",
                         "serve.reader_heartbeat_age_s", float(age),
                         reader=reader)
    for reader in wedged:
        _emit_event(recorder, "reader_wedged", reader=reader,
                    age_s=ages.get(reader), timeout_s=timeout_s)
    return {"ages": ages, "wedged": wedged}
