"""SnapshotWatcher: turn a training run's publish trail into servable state.

The training plane already defines the publication contract:

* the async double-buffered checkpointer publishes snapshots only via
  atomic rename — a published ``ckpt_*.npz`` is never torn;
* ``checkpoint_saved`` journal events mark TRUE durability points and
  carry the snapshot's ``path``, ``step`` and byte size (so the watcher
  needs no directory re-stat on the hot path);
* a corrupt snapshot is quarantined by the trainer's restore path
  (renamed ``*.corrupt``) and announced by a ``checkpoint_fallback``
  event — from the read path's point of view, the run's history just
  rolled BACKWARD past that step.

:class:`SnapshotWatcher` consumes that trail — tailing the obs journal
(:class:`_JournalTail`, which survives truncation and file replacement:
the supervisor restart path rewrites journals underneath a live tailer)
and/or polling the checkpoint directory — CRC-verifies every new
candidate (:meth:`ServableSnapshot.open`), and publishes the newest
verified snapshot through ``on_swap``. DELTA publications
(``DeltaPolicy`` chains) are candidates too: a delta serves only when
its whole chain verifies (a chain through a ``*.corrupt`` base never
resolves), and when the served snapshot is on the candidate's chain the
swap is INCREMENTAL — touched rows overlaid on the still-mapped base
(:meth:`ServableSnapshot.with_delta`), O(touched rows) per link. Swaps are monotone FORWARD except
for exactly one cause: when the currently served step is quarantined (or
its file vanishes with nothing newer), the watcher swaps BACKWARD to the
newest surviving verified snapshot — readers must never keep answering
from state the trainer has rolled back past.

Freshness accounting (through ``fps_tpu.obs``): ``serve.snapshot_step`` /
``serve.snapshot_lag_steps`` gauges (served step vs newest step the
trainer has *written*), ``serve.write_to_servable_s`` (durability →
servable wall-clock lag — the end-to-end freshness SLO),
``serve.swaps{direction=forward|backward}`` and
``serve.rejected_snapshots`` counters.

jax-free; single-threaded by design (call :meth:`poll` from one thread —
the server side is the concurrent part).
"""

from __future__ import annotations

import json
import logging
import os
import time

from fps_tpu.core import retry as _retry
from fps_tpu.core import snapshot_format as fmt
from fps_tpu.serve.snapshot import ServableSnapshot, SnapshotRejected

__all__ = ["SnapshotWatcher", "_JournalTail"]

_log = logging.getLogger("fps_tpu.serve.watcher")


def _emit_metric(recorder, kind: str, name: str, value, **labels) -> None:
    """Metric through an explicit recorder, else the process default
    (``fps_tpu.obs.events``) — same degrade-don't-crash contract."""
    if recorder is not None:
        getattr(recorder, kind)(name, value, **labels)
        return
    from fps_tpu.obs import events

    events.record_metric(kind, name, value, **labels)


def _emit_event(recorder, etype: str, **fields) -> None:
    """Journal event through an explicit recorder, else the process
    default — same contract as :func:`_emit_metric`."""
    if recorder is not None:
        recorder.event(etype, **fields)
        return
    from fps_tpu.obs import events

    events.emit(etype, **fields)


class _JournalTail:
    """Incremental reader of one JSONL journal that survives the file
    being truncated, replaced (rotation / supervisor restart), or not
    existing yet.

    ``read_new()`` returns the complete records appended since the last
    call. Detection: a shrunken file or a changed inode resets the tail
    to offset 0 and re-reads from the top — the caller deduplicates
    (snapshot steps are idempotent keys), which is the right division of
    labor because only the caller knows what "already seen" means. A
    torn final line (live writer mid-append) is buffered until its
    newline arrives.
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._ino: int | None = None
        self._buf = b""

    def read_new(self) -> list[dict]:
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            self._offset, self._ino, self._buf = 0, None, b""
            return []
        if self._ino is not None and (st.st_ino != self._ino
                                      or st.st_size < self._offset):
            # Rotated (new inode) or truncated in place: start over.
            self._offset, self._buf = 0, b""
        self._ino = st.st_ino
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return []
        self._offset += len(data)
        self._buf += data
        out = []
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break
            line, self._buf = self._buf[:nl], self._buf[nl + 1:]
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # torn mid-record at a truncation boundary
        return out


class SnapshotWatcher:
    """Maintain "the newest verified snapshot of ``ckpt_dir``".

    ``journal``: path to an obs journal file (``journal-p0.jsonl``) or a
    directory containing ``journal-*.jsonl`` — tailed for
    ``checkpoint_saved`` / ``checkpoint_fallback`` events. ``poll_dir``
    additionally (or, with no journal, exclusively) lists the directory —
    the journal is an optimization, never the only source of truth, so a
    run without telemetry still serves.

    ``on_swap(snapshot, direction)`` fires on every publish
    (``direction`` is ``"forward"`` or ``"backward"``); wire it to
    :meth:`ReadServer.swap_to`. The callback runs on the polling thread.
    """

    def __init__(self, ckpt_dir: str, *, journal: str | None = None,
                 poll_dir: bool = True, on_swap=None, recorder=None,
                 verify: bool = True):
        if journal is None and not poll_dir:
            raise ValueError("need a journal to tail or poll_dir=True — "
                             "a watcher with no source can never publish")
        self.ckpt_dir = ckpt_dir
        self.on_swap = on_swap
        self.recorder = recorder
        self.verify = verify
        self.current: ServableSnapshot | None = None
        self.poll_dir = poll_dir
        self._tails = []
        self._journal = journal
        if journal is not None:
            self._tails = [_JournalTail(p) for p in _journal_paths(journal)]
        # step -> (path, saved_wall_time) from checkpoint_saved events.
        self._saved_events: dict[int, tuple[str, float]] = {}
        self._quarantined: set[int] = set()
        # Newest step the trainer has WRITTEN (saved events ∪ dir scan) —
        # the freshness reference for serve.snapshot_lag_steps.
        self.max_written_step: int | None = None
        # step -> (st_ino, st_mtime_ns) of a file that failed
        # verification; re-checked only when the file changes (an atomic
        # re-publish of the same step gets a fresh verdict, a known-torn
        # file is not re-read every poll).
        self._rejected: dict[int, tuple] = {}
        # First-rejection holding pen: a verdict is pinned into
        # _rejected only when the SAME (step, identity) fails twice —
        # on a hostile filesystem one failing open can be a stale read
        # of pre-rename content while the durable bytes are fine, and
        # pinning on that would blind the reader to a valid publish
        # forever (the identity keys the REAL file, not what was read).
        self._reject_pending: set = set()
        # Live publication index from the last dir scan ({step:
        # Publication}) — empty in journal-only mode (chain resolution
        # then re-scans inside open_chain).
        self._pubs: dict = {}
        # Chain failures are re-CHECKED every poll (transient by
        # nature) but COUNTED once per (step, head file identity) — a
        # lingering broken chain head must not inflate
        # serve.rejected_snapshots at poll frequency.
        self._chain_rejected_seen: set = set()
        self.swaps = {"forward": 0, "backward": 0}
        self.rejected = 0
        # Storage-brownout degradation: polls that died on a transient
        # filesystem error (EIO on a listdir, a flaky open) are COUNTED
        # and the reader keeps serving last-good mapped state — a
        # misbehaving shared filesystem must never freeze or crash the
        # read plane (docs/resilience.md "Hostile filesystem").
        self.poll_errors = 0
        # Durability → servable wall-clock lag of the LAST publish (the
        # end-to-end freshness SLO sample; also a serve.* gauge).
        self.write_to_servable_s: float | None = None

    # -- sources -----------------------------------------------------------

    def _drain_journal(self) -> None:
        if self._journal is not None:
            # The journal file/dir may be created after the watcher
            # starts (trainer still booting), and a directory grows new
            # journal-*.jsonl members as processes join: re-glob every
            # drain. Existing tails keep their offsets; a tail that
            # turns out to BE the directory (the arg named a dir that
            # did not exist yet at construction) is dropped for its
            # members.
            self._tails = [t for t in self._tails
                           if not os.path.isdir(t.path)]
            known = {t.path for t in self._tails}
            self._tails += [
                _JournalTail(p) for p in _journal_paths(self._journal)
                if p not in known and not os.path.isdir(p)]
        for tail in self._tails:
            for rec in tail.read_new():
                if rec.get("kind") != "event":
                    continue
                et = rec.get("event")
                if et == "checkpoint_saved" and "step" in rec:
                    step = int(rec["step"])
                    path = rec.get("path") or fmt.snapshot_path(
                        self.ckpt_dir, step)
                    self._saved_events[step] = (
                        path, float(rec.get("t") or 0.0))
                    # A save AFTER a fallback at the same step is the
                    # rollback-replay path re-publishing it: the fresh
                    # file supersedes the quarantine verdict (the CRC
                    # gate still decides whether it serves).
                    self._quarantined.discard(step)
                    self._see_step(step)
                elif et == "checkpoint_enqueued" and "step" in rec:
                    self._see_step(int(rec["step"]))
                elif et == "checkpoint_fallback" and "step" in rec:
                    self._quarantined.add(int(rec["step"]))

    def _see_step(self, step: int) -> None:
        if self.max_written_step is None or step > self.max_written_step:
            self.max_written_step = step

    def _scan_dir(self) -> list[int]:
        try:
            _retry.fault_check("listdir", self.ckpt_dir)
            names = os.listdir(self.ckpt_dir)
        except FileNotFoundError:
            names = []
        # Full snapshots AND delta links are candidates — a delta step
        # serves by resolving its chain (fps_tpu.core.snapshot_format).
        self._pubs = fmt.publications(self.ckpt_dir)
        steps = sorted(self._pubs)
        live = set(steps)
        for s in steps:
            self._see_step(s)
        # A *.corrupt sibling is the trainer's quarantine verdict — the
        # on-disk form of a checkpoint_fallback event (poll-only mode
        # must see rollbacks too). A LIVE file at the same step
        # supersedes it: the rollback-replay path re-publishes the step
        # it quarantined, and the fresh snapshot must be servable (the
        # CRC gate still decides — a lingering corrupt live file just
        # lands in the per-inode rejection cache). Quarantined DELTA
        # links count too: any candidate whose chain would pass through
        # one is ineligible until re-published.
        for f in names:
            if f.endswith(".corrupt"):
                base = f[: -len(".corrupt")]
                m = fmt.SNAPSHOT_RE.fullmatch(base)
                dm = fmt.DELTA_RE.fullmatch(base)
                s = int(m.group(1)) if m else (
                    int(dm.group(1)) if dm else None)
                if s is not None and s not in live:
                    self._quarantined.add(s)
        self._quarantined -= live
        return steps

    def _chain_quarantined(self, step: int) -> bool:
        """True when ``step``'s back-chain passes through a quarantined
        step — a reader must never resolve a chain through a
        ``*.corrupt`` base, even when the head file itself is intact."""
        pub = self._pubs.get(step)
        seen = set()
        while pub is not None and pub.kind == "delta":
            if pub.base in self._quarantined:
                return True
            if pub.base in seen:
                return True  # cyclic garbage: never servable
            seen.add(pub.base)
            pub = self._pubs.get(pub.base)
        return False

    # -- the poll ----------------------------------------------------------

    def poll(self) -> ServableSnapshot | None:
        """One pass over all sources; publishes (and returns) a new
        snapshot when one is due, else returns None. Never raises on
        torn/corrupt candidates — they are counted and skipped — and
        never raises on a TRANSIENT filesystem error either (storage
        brownout: EIO/ENOSPC/stale-mount hiccups on the scan or an
        open): the poll degrades to last-good served state, counts
        ``poll_errors`` / ``storage.poll_errors{plane=watcher}``, and
        retries next tick."""
        try:
            return self._poll_once()
        except OSError as e:
            self.poll_errors += 1
            _emit_metric(self.recorder, "inc", "storage.poll_errors", 1,
                         plane="watcher")
            _log.warning("snapshot watcher poll degraded (serving "
                         "last-good, retrying next poll): %r", e)
            return None

    def _poll_once(self) -> ServableSnapshot | None:
        self._drain_journal()
        listed = self._scan_dir() if self.poll_dir else []
        candidates = set(listed) | set(self._saved_events)
        candidates -= self._quarantined
        cur = self.current
        cur_id = _file_id(cur.path) if cur is not None else None
        # Alive = the step is still eligible AND the file on disk is the
        # very inode we mapped (src_id None = hand-built snapshot:
        # degrade to existence). A mismatch is a re-publish.
        cur_alive = (cur is not None and cur.step in candidates
                     and cur_id is not None
                     and (cur.src_id is None or cur_id == cur.src_id))
        swapped = None
        for step in sorted(candidates, reverse=True):
            if cur is not None and step == cur.step:
                if cur_alive:
                    break  # already serving the newest eligible step
                # The served FILE is gone or is no longer the mapped
                # inode: vanished (deleted without a *.corrupt rename,
                # its step lingering in the journal's saved events) or
                # atomically REPLACED (the rollback-replay path
                # re-publishes the very step it quarantined). Try the
                # step fresh — a verified re-publish swaps in place; a
                # torn or missing one falls through to older survivors
                # (a backward swap, exactly like a quarantine).
                snap = self._try_open(step)
                if snap is None:
                    continue
                self._publish(snap, "forward")
                swapped = snap
                break
            # No step < cur.step is ever reached while cur is alive:
            # cur.step is in candidates then, so the descending loop
            # breaks at the step == cur.step branch first — backward
            # swaps happen only past a quarantine/vanish/replace.
            snap = self._try_open(step)
            if snap is None:
                continue
            direction = ("backward" if cur is not None
                         and snap.step < cur.step else "forward")
            self._publish(snap, direction)
            swapped = snap
            break
        if swapped is None and cur is not None and not cur_alive:
            # Served step quarantined/vanished and no candidate verified:
            # keep answering from the mapped (still-valid) pages — the
            # alternative is serving nothing — but surface it. Fires
            # whether the rest of the directory is empty or all torn.
            _emit_metric(self.recorder, "set",
                         "serve.snapshot_lag_steps", float("nan"))
        return swapped

    def _try_open(self, step: int) -> ServableSnapshot | None:
        pub = self._pubs.get(step)
        if step in self._saved_events:
            path = self._saved_events[step][0]
        elif pub is not None:
            path = pub.path
        else:
            path = fmt.snapshot_path(self.ckpt_dir, step)
        delta_m = fmt.DELTA_RE.fullmatch(os.path.basename(path))
        file_id = _file_id(path)
        if file_id is None:
            # Swept/renamed between the candidate scan and this open:
            # gone, retry next poll — never a rejection verdict.
            return None
        if self._rejected.get(step) == file_id:
            return None  # known-bad file; only a re-publish re-checks
        if delta_m is not None and self._chain_quarantined(step):
            # The head file may be pristine, but its chain passes
            # through a *.corrupt base: state past the quarantine is
            # unrecoverable — never resolve through it. Not cached: a
            # re-publish of the base lifts the verdict.
            return None
        try:
            if delta_m is None:
                return ServableSnapshot.open(path, step=step,
                                             verify=self.verify)
            base = int(delta_m.group(2))
            cur = self.current
            if (cur is not None and cur.step == base
                    and step not in self._quarantined
                    and self._cur_matches_disk(cur)):
                # Delta-aware INCREMENTAL hot-swap: the served snapshot
                # is the delta's base — apply the touched rows to the
                # mapped view instead of re-opening the world.
                return cur.with_delta(path, verify=self.verify)
            inc = self._catch_up(cur, step)
            if inc is not None:
                return inc
            return ServableSnapshot.open_chain(self.ckpt_dir, step,
                                               verify=self.verify)
        except FileNotFoundError:
            # The poll-loop race, mid-open this time: a candidate swept
            # between stat and open is skipped, not raised and not
            # counted as a rejection (regression-tested).
            return None
        except (SnapshotRejected, ValueError):
            if delta_m is None:
                self.rejected += 1
                _emit_metric(self.recorder, "inc",
                             "serve.rejected_snapshots", 1)
                # Keyed by (inode, mtime) like every identity check here
                # — mtime alone can collide with an atomic re-publish
                # landing in the same clock tick, pinning a now-valid
                # step as bad. Only SINGLE-file verdicts are cached (a
                # full's content is immutable at that identity), and
                # only once CONFIRMED by a second failing read — one
                # verdict can be a transient stale read of pre-rename
                # content, not evidence about the durable bytes.
                key = (step, file_id)
                if key in self._reject_pending:
                    self._reject_pending.discard(key)
                    self._rejected[step] = file_id
                else:
                    if len(self._reject_pending) > 1024:
                        self._reject_pending.clear()  # bounded memory
                    self._reject_pending.add(key)
                return None
            # A CHAIN failure is not cached — the head file may be
            # pristine while a link was mid-sweep/compaction/quarantine
            # when we walked it; the verdict can lift without the head
            # changing, so eligibility is re-checked next poll (chains
            # are bounded by DeltaPolicy.full_every, the retry is
            # cheap). It is COUNTED once per head identity, though: a
            # lingering broken head polled at 20 Hz must not turn the
            # rejected counter into a poll counter.
            key = (step, file_id)
            if key not in self._chain_rejected_seen:
                if len(self._chain_rejected_seen) > 1024:
                    self._chain_rejected_seen.clear()  # bounded memory
                self._chain_rejected_seen.add(key)
                self.rejected += 1
                _emit_metric(self.recorder, "inc",
                             "serve.rejected_snapshots", 1)
            return None

    def _cur_matches_disk(self, cur) -> bool:
        """The incremental paths extend the served snapshot's IN-MEMORY
        state — legal only while the on-disk publication at that step is
        still the very file (inode+mtime) the snapshot mapped. After a
        quarantine → rollback-replay re-publish, the step number matches
        but the CONTENT may not: overlaying a new delta on the old maps
        would serve rows that exist in no publication. The full-snapshot
        path's ``cur_alive`` check; applied to chain extension."""
        if cur is None or cur.src_id is None:
            return False
        pub = self._pubs.get(cur.step)
        if pub is None or pub.path != cur.path:
            return False
        return _file_id(pub.path) == cur.src_id

    def _catch_up(self, cur, step: int) -> ServableSnapshot | None:
        """Multi-delta incremental catch-up: when the candidate's chain
        passes THROUGH the served step, extend the served snapshot
        link by link (each link verifies just its own delta) instead of
        re-opening and re-CRCing the whole chain from the base full —
        the reader that missed a few polls pays O(missed deltas), not
        O(table). None = not applicable (fall back to open_chain);
        raises like :meth:`ServableSnapshot.with_delta` on bad links."""
        if cur is None or not self._cur_matches_disk(cur):
            return None
        pubs = self._pubs or fmt.publications(self.ckpt_dir)
        try:
            members = fmt.chain_members(pubs, step)
        except fmt.ChainError:
            return None
        idx = next((i for i, p in enumerate(members)
                    if p.step == cur.step), None)
        if idx is None:
            return None
        tail = members[idx + 1:]
        if not tail or any(p.kind != "delta"
                           or p.step in self._quarantined for p in tail):
            return None
        snap = cur
        for link in tail:
            snap = snap.with_delta(link.path, verify=self.verify)
        return snap

    def _publish(self, snap: ServableSnapshot, direction: str) -> None:
        self.current = snap
        self.swaps[direction] += 1
        now = time.time()
        saved = self._saved_events.get(snap.step)
        if saved is not None and saved[1] > 0:
            write_wall = saved[1]
        else:
            try:
                write_wall = os.stat(snap.path).st_mtime
            except OSError:
                write_wall = now
        _emit_metric(self.recorder, "inc", "serve.swaps", 1,
                     direction=direction)
        _emit_metric(self.recorder, "set", "serve.snapshot_step",
                     float(snap.step))
        if self.max_written_step is not None:
            _emit_metric(self.recorder, "set", "serve.snapshot_lag_steps",
                         float(self.max_written_step - snap.step))
        self.write_to_servable_s = max(0.0, now - write_wall)
        _emit_metric(self.recorder, "set", "serve.write_to_servable_s",
                     self.write_to_servable_s)
        # Journal event beside the counters: the swap becomes a span in
        # the exported causal trace (tools/trace_export.py) — serve-side
        # hot-swaps link into the same tree as the publish that fed them.
        _emit_event(self.recorder, "serve_swap", step=int(snap.step),
                    direction=direction,
                    write_to_servable_s=round(self.write_to_servable_s,
                                              4))
        if self.on_swap is not None:
            self.on_swap(snap, direction)

    def run(self, *, interval_s: float = 0.2, stop=None,
            max_polls: int | None = None) -> None:
        """Poll loop: every ``interval_s`` until ``stop`` (a
        ``threading.Event``) is set or ``max_polls`` polls ran."""
        n = 0
        while (stop is None or not stop.is_set()) and (
                max_polls is None or n < max_polls):
            self.poll()
            n += 1
            if stop is not None:
                stop.wait(interval_s)
            else:
                time.sleep(interval_s)


def _file_id(path: str):
    """(st_ino, st_mtime_ns) identity of ``path`` (None when gone) —
    compared against :attr:`ServableSnapshot.src_id` so a re-publish of
    the served step is detected, not just a vanished file."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns)


def _journal_paths(journal: str) -> list[str]:
    """A journal argument is a file path or a directory holding
    ``journal-*.jsonl`` (the ``--obs-dir`` layout)."""
    if os.path.isdir(journal):
        return sorted(
            os.path.join(journal, f) for f in os.listdir(journal)
            if f.startswith("journal-") and f.endswith(".jsonl"))
    return [journal]
