"""fps_tpu.serve — the read path: publish snapshots to query traffic.

The serving half of the parameter-server abstraction (Parameter Box,
PAPERS.md): everything up to here trains; this subsystem answers. A
:class:`SnapshotWatcher` turns the training plane's publish trail
(atomic-rename ``ckpt_*.npz`` snapshots + ``checkpoint_saved`` journal
events) into a stream of CRC-verified, read-only-mmapped
:class:`ServableSnapshot` publications; a :class:`ReadServer` answers
batched pull-by-id and model-head queries (MF top-k, logreg/PA scoring)
against the current one, hot-swapping to each newer snapshot by a single
reference flip — in-flight requests finish on the snapshot they started
on, and the swap cost is independent of table size. ``docs/serving.md``
is the architecture note; the freshness SLO ("write→servable" lag) and
swap/rollback semantics live there.

Delta publications (``DeltaPolicy`` chains on the write side) hot-swap
INCREMENTALLY — ``ServableSnapshot.with_delta`` overlays the touched
rows on the still-mapped base (:class:`DeltaView`) instead of re-opening
the world — and the single reader grows into a step-fenced FLEET
(:mod:`fps_tpu.serve.fleet`): N readers over one snapshot dir whose
swaps are coordinated by a shared fence no reader ever answers behind.

jax-optional by construction (stdlib + numpy; the on-disk contract comes
from the jax-free :mod:`fps_tpu.core.snapshot_format`): ``tools/serve.py``
runs this whole plane on a machine with no accelerator runtime.
"""

from fps_tpu.serve.admission import AdmissionController
from fps_tpu.serve.fleet import (
    FleetReader,
    ReadAutoscaler,
    ServingFleet,
    StepFence,
    liveness_check,
    scan_heartbeats,
    tiering_hot_ids,
)
from fps_tpu.serve.net import JsonlClient, TcpServe, handle_request
from fps_tpu.serve.server import CoalesceConfig, NoSnapshotError, ReadServer
from fps_tpu.serve.shadow import ShadowGate, ShadowScorer
from fps_tpu.serve.snapshot import (
    DeltaView,
    ServableSnapshot,
    SnapshotRejected,
    materialize,
)
from fps_tpu.serve.watcher import SnapshotWatcher
from fps_tpu.serve.wire import (
    ProtocolVersionError,
    ServerBusyError,
    TornFrameError,
    WireClient,
    WireError,
)

__all__ = [
    "AdmissionController",
    "CoalesceConfig",
    "DeltaView",
    "FleetReader",
    "JsonlClient",
    "NoSnapshotError",
    "ProtocolVersionError",
    "ReadAutoscaler",
    "ReadServer",
    "ServableSnapshot",
    "ServerBusyError",
    "ServingFleet",
    "ShadowGate",
    "ShadowScorer",
    "SnapshotRejected",
    "SnapshotWatcher",
    "StepFence",
    "TcpServe",
    "TornFrameError",
    "WireClient",
    "WireError",
    "handle_request",
    "liveness_check",
    "materialize",
    "scan_heartbeats",
    "tiering_hot_ids",
]
