"""Shadow serving: score old-vs-new snapshots before the fleet swaps.

A multi-tenant fleet cannot let "newest verified snapshot" be the whole
promotion story — a tenant's training regression (bad shard, poisoned
chunk that slipped the guards, a bug in a new workload revision) would
hot-swap straight into its serving path. This module adds the promotion
gate:

* :class:`ShadowScorer` — the write-side judge. It watches the tenant's
  snapshot dir with its own verifying
  :class:`~fps_tpu.serve.watcher.SnapshotWatcher`, and for every fresh
  candidate scores the CURRENTLY APPROVED snapshot and the candidate
  side by side with a caller-supplied ``score_fn(snapshot) -> float``
  (higher is better; e.g. accuracy on a held-out probe set). The
  candidate is promoted iff ``new >= old + min_delta``; otherwise the
  decision is HELD and re-judged only when a newer candidate appears.
* :class:`ShadowGate` — the read-side contract: an atomic-rename JSON
  (``<ckpt_dir>/fleet/shadow_gate.json``, next to the step fence) naming
  the newest APPROVED step. A gated
  :class:`~fps_tpu.serve.fleet.FleetReader` caps its readiness (and
  fence advance) at the approved step, so an unapproved publication is
  simply invisible to the fleet.

Staleness contract (docs/STALENESS.md): a held promotion means the fleet
keeps answering from the old approved snapshot — LOST FRESHNESS, never
wrong answers. The gate can only hold the fence back, never push it past
what quorum verification allows.

jax-free (stdlib + numpy) like the rest of ``fps_tpu.serve``.
"""

from __future__ import annotations

import os
import time

from fps_tpu.serve.snapshot import ServableSnapshot, SnapshotRejected
from fps_tpu.serve.watcher import SnapshotWatcher, _emit_event, \
    _emit_metric

__all__ = ["ShadowGate", "ShadowScorer", "GATE_NAME"]

GATE_NAME = "shadow_gate.json"
# Default promotion bar: the candidate may be this much WORSE than the
# approved snapshot and still promote — freshness is worth a little
# noise, a real regression is not.
DEFAULT_MIN_DELTA = -0.02


class ShadowGate:
    """The approved-step record one tenant's scorer and readers share."""

    def __init__(self, ckpt_dir: str):
        # Late import breaks the fleet<->shadow import cycle (fleet
        # imports ShadowGate for its gated readers).
        from fps_tpu.serve import fleet as _fleet
        self._fleet = _fleet
        self.dir = os.path.join(ckpt_dir, _fleet.FLEET_DIR)
        self.path = os.path.join(self.dir, GATE_NAME)

    def read_record(self) -> dict | None:
        rec = self._fleet._read_json(self.path)
        if not isinstance(rec, dict) or "approved_step" not in rec:
            return None
        return rec

    def approved_step(self) -> int | None:
        """Newest approved step; None while nothing is approved (a gated
        fleet serves nothing until the scorer's first promotion)."""
        rec = self.read_record()
        return None if rec is None else int(rec["approved_step"])

    def approve(self, step: int, *, score_new=None, score_old=None) -> dict:
        """Promote ``step`` (forward-monotone; stale approvals no-op)."""
        cur = self.approved_step()
        if cur is not None and step <= cur:
            return self.read_record()
        rec = {"approved_step": int(step), "t": time.time(),
               "score_new": score_new, "score_old": score_old}
        os.makedirs(self.dir, exist_ok=True)
        self._fleet._atomic_write_json(self.path, rec)
        return rec


class ShadowScorer:
    """Judge every fresh candidate against the approved snapshot.

    Args:
      ckpt_dir: the tenant's snapshot dir (the gate file lands in its
        ``fleet/`` subdir).
      score_fn: ``score_fn(ServableSnapshot) -> float``, higher better.
      min_delta: promotion bar — promote iff
        ``score(new) >= score(old) + min_delta``.
      recorder: obs recorder for ``serve.shadow_*`` metrics/events.
      verify: full-verify candidates before judging (as the readers do).
    """

    def __init__(self, ckpt_dir: str, score_fn, *,
                 min_delta: float = DEFAULT_MIN_DELTA,
                 journal: str | None = None, recorder=None,
                 verify: bool = True):
        self.ckpt_dir = ckpt_dir
        self.score_fn = score_fn
        self.min_delta = float(min_delta)
        self.recorder = recorder
        self.verify = verify
        self.gate = ShadowGate(ckpt_dir)
        self.promotions = 0
        self.holds = 0
        self._candidate: ServableSnapshot | None = None
        self._held_step: int | None = None  # judged-and-held; re-judge
        #                                     only a NEWER candidate
        self.watcher = SnapshotWatcher(
            ckpt_dir, journal=journal, recorder=recorder,
            on_swap=self._on_candidate, verify=verify)

    def _on_candidate(self, snap: ServableSnapshot, _direction: str):
        self._candidate = snap

    def _open_approved(self, step: int) -> ServableSnapshot | None:
        try:
            return ServableSnapshot.open_chain(self.ckpt_dir, step,
                                               verify=self.verify)
        except (FileNotFoundError, SnapshotRejected):
            return None

    def poll(self) -> dict | None:
        """One judging pass. Returns the decision record when a fresh
        candidate was judged (``decision: promoted | held``), else None.
        """
        self.watcher.poll()
        cand = self._candidate
        if cand is None:
            return None
        approved = self.gate.approved_step()
        if approved is not None and cand.step <= approved:
            return None
        if self._held_step is not None and cand.step <= self._held_step:
            return None  # already judged and held; wait for newer
        score_new = float(self.score_fn(cand))
        score_old = None
        if approved is not None:
            old = self._open_approved(approved)
            # An approved snapshot that is no longer openable (pruned,
            # quarantined) cannot hold the gate: judge unconditionally.
            score_old = None if old is None else float(self.score_fn(old))
        promoted = (score_old is None
                    or score_new >= score_old + self.min_delta)
        rec = {"step": int(cand.step), "prev_approved": approved,
               "score_new": score_new, "score_old": score_old,
               "decision": "promoted" if promoted else "held"}
        if promoted:
            self.gate.approve(cand.step, score_new=score_new,
                              score_old=score_old)
            self.promotions += 1
            self._held_step = None
            _emit_metric(self.recorder, "inc", "serve.shadow_promotions", 1)
            _emit_event(self.recorder, "serve.shadow_promoted", **rec)
        else:
            self.holds += 1
            self._held_step = int(cand.step)
            _emit_metric(self.recorder, "inc", "serve.shadow_held", 1)
            _emit_event(self.recorder, "serve.shadow_held", **rec)
        return rec
