"""Admission control for the serve wire: cost-weighted, latency-adaptive.

PR 16's shed gate was a plain in-flight semaphore — every request cost
"1", so sixty-four cheap single-row pulls and sixty-four dense top-k
matmuls both filled the house, and the limit had no opinion about
whether the server was actually keeping its latency promise. This
module replaces it with an :class:`AdmissionController`:

* **per-op cost weights** — a request is admitted against a COST
  budget, not a slot count: a ``topk`` (whole-item-table matmul) weighs
  ~8x a ``pull`` gather; a batched ``multi`` frame weighs the SUM of
  its members, so one frame carrying 500 lookups is charged like 500
  lookups (batching amortizes framing overhead, never admission).
* **latency-target AIMD** — with a ``target_latency_s`` set, the
  effective cost limit tracks the latency the server actually delivers:
  each completed request's latency feeds an EWMA; over-target
  completions shrink the limit multiplicatively, under-target
  completions regrow it additively (to at most the configured ceiling).
  In-flight cost IS the queue-depth signal — shedding starts exactly
  when queued work would push the p99 past its target, not at an
  arbitrary connection count.
* **lost work, never lost correctness** — a shed is the same retryable
  ``BUSY`` frame it always was (``net.shed_requests``, the shed-rate
  SLO in ``fps_tpu.obs.fleet``); the client backs off and resends
  (``docs/STALENESS.md``).

The autoscaler (:class:`fps_tpu.serve.fleet.ReadAutoscaler`) reads
:meth:`stats` — sustained shedding or a collapsed limit factor on one
reader is exactly the latency-SLO-burn signal that spawns another.

Stdlib-only and lock-disciplined: one mutex, held for arithmetic only.
"""

from __future__ import annotations

import threading

__all__ = ["AdmissionController", "DEFAULT_COST_WEIGHTS"]

# Relative op costs, calibrated from the serve bench's per-op latency
# ratios (a topk pays a whole-item-table matmul; score is a gather plus
# a reduction; stats touches no table).
DEFAULT_COST_WEIGHTS = {
    "pull": 1.0,
    "score": 2.0,
    "topk": 8.0,
    "stats": 0.25,
}
_UNKNOWN_OP_COST = 1.0


class AdmissionController:
    """Cost-budget admission with an AIMD latency governor.

    ``max_cost`` is the ceiling on concurrently-executing cost (the
    semaphore generalization: ``max_cost=N`` with unit weights is the
    old ``max_inflight=N``). ``target_latency_s=None`` disables the
    governor — the limit stays pinned at ``max_cost``.

    thread-safety: all state behind one lock; ``try_admit``/``release``
    are O(1).
    """

    def __init__(self, *, max_cost: float = 64.0,
                 target_latency_s: float | None = None,
                 weights: dict | None = None,
                 min_limit_fraction: float = 0.125,
                 decrease: float = 0.9, increase: float = 0.02,
                 ewma_alpha: float = 0.2):
        if max_cost <= 0:
            raise ValueError(f"max_cost must be > 0, got {max_cost}")
        self.max_cost = float(max_cost)
        self.target_latency_s = (None if target_latency_s is None
                                 else float(target_latency_s))
        self.weights = dict(DEFAULT_COST_WEIGHTS if weights is None
                            else weights)
        self._min_fraction = float(min_limit_fraction)
        self._decrease = float(decrease)
        self._increase = float(increase)
        self._alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._inflight_cost = 0.0
        self._factor = 1.0  # AIMD multiplier on max_cost
        self._lat_ewma: float | None = None
        self.admitted = 0
        self.rejected = 0

    # -- cost model ---------------------------------------------------------

    def cost_of(self, req) -> float:
        """Cost of one decoded request dict. A ``multi`` frame costs the
        sum of its members — admission charges WORK, not frames."""
        if not isinstance(req, dict):
            return _UNKNOWN_OP_COST
        op = req.get("op")
        if op == "multi":
            reqs = req.get("reqs")
            if not isinstance(reqs, list):
                return _UNKNOWN_OP_COST
            return sum(self.cost_of(r) for r in reqs) or _UNKNOWN_OP_COST
        return float(self.weights.get(op, _UNKNOWN_OP_COST))

    # -- admit / release ----------------------------------------------------

    def limit(self) -> float:
        """Current effective cost limit (AIMD-governed)."""
        with self._lock:
            return self.max_cost * self._factor

    def try_admit(self, cost: float) -> bool:
        """Admit ``cost`` units of work, or refuse (the caller sheds
        with BUSY). An idle server always admits — one request larger
        than the whole budget must degrade to serial execution, never
        starve forever."""
        cost = float(cost)
        with self._lock:
            limit = self.max_cost * self._factor
            if (self._inflight_cost > 0
                    and self._inflight_cost + cost > limit):
                self.rejected += 1
                return False
            self._inflight_cost += cost
            self.admitted += 1
            return True

    def release(self, cost: float, latency_s: float | None = None) -> None:
        """Return ``cost`` to the budget; feed the request's measured
        latency to the AIMD governor."""
        with self._lock:
            self._inflight_cost = max(0.0, self._inflight_cost - cost)
            if latency_s is None or self.target_latency_s is None:
                return
            self._lat_ewma = (latency_s if self._lat_ewma is None
                              else (1 - self._alpha) * self._lat_ewma
                              + self._alpha * latency_s)
            if self._lat_ewma > self.target_latency_s:
                # Multiplicative decrease: the server is missing its
                # latency target — admit less until it recovers.
                self._factor = max(self._min_fraction,
                                   self._factor * self._decrease)
            else:
                self._factor = min(1.0, self._factor + self._increase)

    # -- signals ------------------------------------------------------------

    def inflight_cost(self) -> float:
        with self._lock:
            return self._inflight_cost

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_cost": self.max_cost,
                "limit": self.max_cost * self._factor,
                "limit_factor": self._factor,
                "inflight_cost": self._inflight_cost,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "latency_ewma_s": self._lat_ewma,
                "target_latency_s": self.target_latency_s,
            }
