"""ReadServer: answer pull-by-id and model-head queries over snapshots.

The serving half of the parameter-server abstraction (Parameter Box,
PAPERS.md): batched reads against *published* parameter state. One
:class:`ReadServer` holds a reference to the current
:class:`~fps_tpu.serve.snapshot.ServableSnapshot` and answers

* ``pull(table, ids)``            — batched row lookup (the PS wire op);
* ``score_linear(ids, vals)``     — sparse linear scores: logreg
  probability / PA margin over a weight table (column 0 is the weight
  for every optimizer, matching ``predict_proba_host``);
* ``topk(users, k)``              — MF user×item dot-product top-k over
  the item table and the snapshot's EXPORTED user factors;
* ``stats()``                     — step, request/latency digest, swap
  and freshness counters.

**Hot-swap contract.** :meth:`swap_to` is a single attribute rebind — a
pointer flip whose latency is independent of table size (no data moves;
the snapshot was mapped when it was opened). Every request reads
``self._snap`` exactly ONCE and runs entirely against that object, so an
in-flight batched lookup completes on the snapshot it started on while
later requests see the new one; old maps stay valid until their last
reference drops (rename-only publication — see ``serve/snapshot.py``).
No locks on the read path.

Latency: every request is timed into a bounded reservoir (plus a
``serve.request_seconds`` histogram and ``serve.requests`` /
``serve.rows`` counters through ``fps_tpu.obs``); :meth:`latency_s`
reports p50/p99 — the numbers ``bench.py serve`` publishes. With a
recorder attached, that is three metric records PER REQUEST (a JSONL
sink writes three lines each) — the price of exact sample-level
quantiles in the obs digest. High-qps paths that only need the local
digest pass ``recorder=None`` (as ``bench.py serve`` does) and read
the reservoir through :meth:`stats`.

thread-safety: the swap is a single reference assignment (atomic under
the GIL) and requests bind it once; the latency reservoir and the
request/row totals update under their own locks (post-lookup accounting
only — the data path itself stays lock-free). Many request threads + one
watcher thread is the intended topology.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from fps_tpu.serve.snapshot import ServableSnapshot
from fps_tpu.serve.watcher import SnapshotWatcher, _emit_metric

__all__ = ["ReadServer", "NoSnapshotError"]


class NoSnapshotError(RuntimeError):
    """No servable snapshot has been published yet."""


class _LatencyReservoir:
    """Bounded ring of request latencies with exact quantiles over the
    retained window (the last ``capacity`` requests)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._buf = np.zeros(capacity, np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def add(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._n % self.capacity] = seconds
            self._n += 1

    def quantiles(self, qs=(0.5, 0.99)) -> dict[str, float] | None:
        with self._lock:
            n = min(self._n, self.capacity)
            if not n:
                return None
            window = np.sort(self._buf[:n].copy())
        return {f"p{int(q * 100)}": float(
            window[min(n - 1, int(q * (n - 1) + 0.5))]) for q in qs}

    @property
    def count(self) -> int:
        return self._n


class ReadServer:
    """Model-agnostic read server over a (possibly live) run directory.

    Construct around an initial snapshot, or with none and let a
    :class:`SnapshotWatcher` publish into :meth:`swap_to`.
    :meth:`ReadServer.over` builds the common pairing in one call.
    """

    def __init__(self, snapshot: ServableSnapshot | None = None, *,
                 recorder=None):
        self._snap = snapshot
        self.recorder = recorder
        self.latency = _LatencyReservoir()
        # Request/row totals mutate from every handler thread; the lock
        # keeps them exact so stats() agrees with the obs counters
        # (whose Recorder locks internally).
        self._count_lock = threading.Lock()
        self.requests = 0
        self.rows_served = 0

    @classmethod
    def over(cls, ckpt_dir: str, *, journal: str | None = None,
             recorder=None, verify: bool = True
             ) -> tuple["ReadServer", SnapshotWatcher]:
        """``(server, watcher)`` wired together over ``ckpt_dir``; call
        ``watcher.poll()`` (or run it on a thread) to publish."""
        server = cls(recorder=recorder)
        watcher = SnapshotWatcher(
            ckpt_dir, journal=journal, recorder=recorder,
            on_swap=lambda snap, _direction: server.swap_to(snap),
            verify=verify)
        watcher.poll()
        return server, watcher

    # -- publication -------------------------------------------------------

    def swap_to(self, snapshot: ServableSnapshot | None) -> None:
        """Atomic hot swap: one reference rebind, no data movement — safe
        to call (from the watcher thread) while requests are in flight;
        each request keeps the snapshot it bound at entry. ``None``
        un-publishes: later requests refuse with NoSnapshotError (the
        fleet's quarantine-rollback path uses this rather than answer
        ahead of a rolled-back fence)."""
        self._snap = snapshot

    @property
    def snapshot(self) -> ServableSnapshot:
        snap = self._snap
        if snap is None:
            raise NoSnapshotError(
                "no servable snapshot published yet — has the trainer "
                "saved (and the watcher polled) at least once?")
        return snap

    # -- request plumbing --------------------------------------------------

    def _done(self, op: str, t0: float, rows: int) -> None:
        dt = time.perf_counter() - t0
        self.latency.add(dt)
        with self._count_lock:
            self.requests += 1
            self.rows_served += rows
        _emit_metric(self.recorder, "inc", "serve.requests", 1, op=op)
        _emit_metric(self.recorder, "inc", "serve.rows", max(rows, 0))
        _emit_metric(self.recorder, "observe", "serve.request_seconds", dt,
                     op=op)

    # -- query surface -----------------------------------------------------

    def pull(self, table: str, ids) -> tuple[int, np.ndarray]:
        """Batched pull-by-id. Returns ``(step, values)`` — the step tags
        which publish answered, so a client can reason about freshness."""
        t0 = time.perf_counter()
        snap = self.snapshot  # bound ONCE: in-flight work survives swaps
        out = snap.lookup(table, ids)
        self._done("pull", t0, int(np.asarray(ids).size))
        return snap.step, out

    def score_linear(self, feat_ids, feat_vals, *, table: str = "weights",
                     link: str = "sigmoid") -> tuple[int, np.ndarray]:
        """Sparse linear model scores (logreg ``link="sigmoid"``, PA /
        raw margin ``link="none"``) — the serving twin of
        ``predict_proba_host``: column 0 of the pulled rows is the
        weight for every optimizer, padding ids contribute 0."""
        t0 = time.perf_counter()
        snap = self.snapshot
        feat_ids = np.asarray(feat_ids, np.int64)
        feat_vals = np.asarray(feat_vals)
        rows = snap.lookup(table, feat_ids.reshape(-1))
        w = rows[:, 0].reshape(feat_ids.shape)
        logit = np.sum(w * feat_vals, axis=-1)
        out = 1.0 / (1.0 + np.exp(-logit)) if link == "sigmoid" else logit
        self._done("score", t0, int(feat_ids.size))
        return snap.step, out

    def topk(self, users, k: int = 10, *, item_table: str = "item_factors",
             user_leaf: int = 0) -> tuple[int, np.ndarray, np.ndarray]:
        """MF recommendation head: top-``k`` items per user by dot
        product of the snapshot's exported user factors (``ls::<leaf>``,
        logical user order — the Trainer checkpoint path's form) against
        the item table. Returns ``(step, item_ids (U, k), scores (U, k))``.
        """
        t0 = time.perf_counter()
        if k < 1:
            # argpartition on k<=0 returns arbitrary columns claiming
            # ok — loud refusal, like negative user ids and raw ls.
            raise ValueError(f"k must be >= 1, got {k}")
        snap = self.snapshot
        if snap.local_state_format != "exported":
            raise ValueError(
                "topk needs user factors in the EXPORTED (logical-order) "
                f"local-state form; snapshot step {snap.step} stores "
                f"{snap.local_state_format!r} — checkpoint through the "
                "Trainer path")
        if user_leaf >= len(snap.local_state):
            raise ValueError(
                f"snapshot step {snap.step} has {len(snap.local_state)} "
                f"local-state leaves, no leaf {user_leaf}")
        users = np.asarray(users, np.int64)
        factors = snap.local_state[user_leaf]
        if users.size and (int(users.min(initial=0)) < 0
                           or int(users.max(initial=-1))
                           >= factors.shape[0]):
            # No negative-index wraparound: serving user NU-1's items for
            # user -1 would be silently wrong data, not an error.
            raise IndexError(
                f"user ids must be in [0, {factors.shape[0]}); got "
                f"[{int(users.min())}, {int(users.max())}]")
        p = factors[users]  # (U, rank)
        q = snap.table(item_table)  # (I, rank)
        scores = p @ np.asarray(q).T  # (U, I) — q stays the mapped pages
        k = min(k, scores.shape[1])
        top = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        order = np.argsort(
            -np.take_along_axis(scores, top, axis=1), axis=1)
        items = np.take_along_axis(top, order, axis=1)
        self._done("topk", t0, int(users.size) * k)
        return snap.step, items, np.take_along_axis(scores, items, axis=1)

    # -- digest ------------------------------------------------------------

    def latency_s(self) -> dict[str, float] | None:
        """``{"p50": s, "p99": s}`` over the retained request window."""
        return self.latency.quantiles()

    def stats(self) -> dict:
        snap = self._snap
        lat = self.latency_s() or {}
        return {
            "step": None if snap is None else snap.step,
            "tables": sorted(snap.tables) if snap is not None else [],
            "requests": self.requests,
            "rows_served": self.rows_served,
            "latency_p50_s": lat.get("p50"),
            "latency_p99_s": lat.get("p99"),
        }
