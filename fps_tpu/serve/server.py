"""ReadServer: answer pull-by-id and model-head queries over snapshots.

The serving half of the parameter-server abstraction (Parameter Box,
PAPERS.md): batched reads against *published* parameter state. One
:class:`ReadServer` holds a reference to the current
:class:`~fps_tpu.serve.snapshot.ServableSnapshot` and answers

* ``pull(table, ids)``            — batched row lookup (the PS wire op);
* ``score_linear(ids, vals)``     — sparse linear scores: logreg
  probability / PA margin over a weight table (column 0 is the weight
  for every optimizer, matching ``predict_proba_host``);
* ``topk(users, k)``              — MF user×item dot-product top-k over
  the item table and the snapshot's EXPORTED user factors;
* ``stats()``                     — step, request/latency digest, swap
  and freshness counters.

**Hot-swap contract.** :meth:`swap_to` is a single attribute rebind — a
pointer flip whose latency is independent of table size (no data moves;
the snapshot was mapped when it was opened). Every request reads
``self._snap`` exactly ONCE and runs entirely against that object, so an
in-flight batched lookup completes on the snapshot it started on while
later requests see the new one; old maps stay valid until their last
reference drops (rename-only publication — see ``serve/snapshot.py``).
No locks on the read path.

Latency: every request is timed into a bounded reservoir (plus a
``serve.request_seconds`` histogram and ``serve.requests`` /
``serve.rows`` counters through ``fps_tpu.obs``); :meth:`latency_s`
reports p50/p99 — the numbers ``bench.py serve`` publishes. With a
recorder attached, that is three metric records PER REQUEST (a JSONL
sink writes three lines each) — the price of exact sample-level
quantiles in the obs digest. High-qps paths that only need the local
digest pass ``recorder=None`` (as ``bench.py serve`` does) and read
the reservoir through :meth:`stats`.

thread-safety: the swap is a single reference assignment (atomic under
the GIL) and requests bind it once; the latency reservoir and the
request/row totals update under their own locks (post-lookup accounting
only — the data path itself stays lock-free). Many request threads + one
watcher thread is the intended topology.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from fps_tpu.serve.snapshot import ServableSnapshot, materialize
from fps_tpu.serve.watcher import SnapshotWatcher, _emit_metric

__all__ = ["ReadServer", "NoSnapshotError", "CoalesceConfig"]


class NoSnapshotError(RuntimeError):
    """No servable snapshot has been published yet."""


class CoalesceConfig:
    """Tuning for the request coalescer (:class:`_Coalescer`).

    * ``max_batch`` — most requests merged into one gather batch;
    * ``max_delay_s`` — how long a LEADER may hold a non-full batch
      open waiting for more arrivals. Only applied while another batch
      is already executing (the server is busy, so waiting is free
      concurrency, not added idle latency): **an idle server never
      adds latency** — the first request on a quiet server executes
      immediately, alone (``docs/STALENESS.md``).
    * ``max_queue`` — bound on queued-not-yet-batched requests; a
      request arriving over the bound executes SOLO instead of queueing
      (bounded memory, never unbounded latency — admission control in
      ``serve/net.py`` sheds before this bound matters in practice).
    """

    __slots__ = ("max_batch", "max_delay_s", "max_queue")

    def __init__(self, max_batch: int = 256, max_delay_s: float = 0.0,
                 max_queue: int = 2048):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        self.max_queue = int(max_queue)


class _Pending:
    """One queued call awaiting its batch: ``(kind, payload)`` in,
    result or exception out, an Event for the waiting handler thread."""

    __slots__ = ("kind", "payload", "t0", "result", "error", "event")

    def __init__(self, kind: str, payload: dict, t0: float):
        self.kind = kind
        self.payload = payload
        self.t0 = t0
        self.result = None
        self.error: BaseException | None = None
        self.event = threading.Event()


class _Coalescer:
    """Bounded request-combining queue: concurrently-queued pull/score/
    topk calls merge into ONE batch executed against ONE snapshot
    binding (so every member answers from the same generation), one
    fancy-index gather per table (``ReadServer._run_batch``).

    Combiner pattern: the first submitter with no active leader becomes
    the LEADER, drains the queue in ``max_batch`` slices, executes each
    slice, and wakes the waiters; everyone else parks on an Event. The
    leader keeps draining until the queue is empty (so overflow slices
    are never orphaned), then returns its own result. Per-request
    latency is measured from SUBMIT, so the coalescing delay is visible
    in the p99 the bench reports — bounded added latency, never hidden.
    """

    def __init__(self, server: "ReadServer", cfg: CoalesceConfig):
        self._server = server
        self.cfg = cfg
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._leader_active = False
        self._executing = False

    def submit(self, kind: str, payload: dict, t0: float):
        entry = _Pending(kind, payload, t0)
        with self._lock:
            if len(self._pending) >= self.cfg.max_queue:
                solo = True  # over the bound: execute alone, don't queue
            else:
                solo = False
                self._pending.append(entry)
                lead = not self._leader_active
                if lead:
                    self._leader_active = True
                busy = self._executing
        if solo:
            return self._server._run_solo(kind, payload, t0)
        if not lead:
            # ~60s is far beyond any legitimate batch execution; a
            # timeout here means the leader died un-catchably.
            if not entry.event.wait(timeout=60.0):
                raise RuntimeError(
                    "coalesced request abandoned: batch leader never "
                    "completed")
            if entry.error is not None:
                raise entry.error
            return entry.result
        return self._lead(entry, busy)

    def _lead(self, own: _Pending, busy: bool):
        cfg = self.cfg
        if busy and cfg.max_delay_s > 0:
            # Another batch is mid-flight: hold the door open briefly so
            # the queue fills — the knob trades a BOUNDED latency add
            # for a bigger amortized gather. Never taken when idle.
            deadline = time.perf_counter() + cfg.max_delay_s
            while time.perf_counter() < deadline:
                with self._lock:
                    if len(self._pending) >= cfg.max_batch:
                        break
                time.sleep(min(cfg.max_delay_s / 8, 0.001))
        try:
            while True:
                with self._lock:
                    if not self._pending:
                        self._leader_active = False
                        break
                    batch = self._pending[:cfg.max_batch]
                    del self._pending[:cfg.max_batch]
                    self._executing = True
                try:
                    self._server._execute_entries(batch)
                finally:
                    with self._lock:
                        self._executing = False
        except BaseException as e:
            # The leader must never park waiters forever: fail anything
            # still queued, release leadership, then surface.
            with self._lock:
                orphans = self._pending
                self._pending = []
                self._leader_active = False
                self._executing = False
            for o in orphans:
                o.error = e
                o.event.set()
            if own.error is None and not own.event.is_set():
                own.error = e
                own.event.set()
            raise
        if own.error is not None:
            raise own.error
        return own.result

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)


class _LatencyReservoir:
    """Bounded ring of request latencies with exact quantiles over the
    retained window (the last ``capacity`` requests)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._buf = np.zeros(capacity, np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def add(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._n % self.capacity] = seconds
            self._n += 1

    def quantiles(self, qs=(0.5, 0.99)) -> dict[str, float] | None:
        with self._lock:
            n = min(self._n, self.capacity)
            if not n:
                return None
            window = np.sort(self._buf[:n].copy())
        return {f"p{int(q * 100)}": float(
            window[min(n - 1, int(q * (n - 1) + 0.5))]) for q in qs}

    @property
    def count(self) -> int:
        return self._n


class ReadServer:
    """Model-agnostic read server over a (possibly live) run directory.

    Construct around an initial snapshot, or with none and let a
    :class:`SnapshotWatcher` publish into :meth:`swap_to`.
    :meth:`ReadServer.over` builds the common pairing in one call.
    """

    def __init__(self, snapshot: ServableSnapshot | None = None, *,
                 recorder=None, coalesce: CoalesceConfig | None = None):
        self._snap = snapshot
        self.recorder = recorder
        self.latency = _LatencyReservoir()
        # Request/row totals mutate from every handler thread; the lock
        # keeps them exact so stats() agrees with the obs counters
        # (whose Recorder locks internally).
        self._count_lock = threading.Lock()
        self.requests = 0
        self.rows_served = 0
        # Batching accounting (the coalescer and multi() both feed it).
        self.batches = 0
        self.batched_requests = 0
        self._coalescer = (None if coalesce is None
                           else _Coalescer(self, coalesce))

    @classmethod
    def over(cls, ckpt_dir: str, *, journal: str | None = None,
             recorder=None, verify: bool = True
             ) -> tuple["ReadServer", SnapshotWatcher]:
        """``(server, watcher)`` wired together over ``ckpt_dir``; call
        ``watcher.poll()`` (or run it on a thread) to publish."""
        server = cls(recorder=recorder)
        watcher = SnapshotWatcher(
            ckpt_dir, journal=journal, recorder=recorder,
            on_swap=lambda snap, _direction: server.swap_to(snap),
            verify=verify)
        watcher.poll()
        return server, watcher

    # -- publication -------------------------------------------------------

    def swap_to(self, snapshot: ServableSnapshot | None) -> None:
        """Atomic hot swap: one reference rebind, no data movement — safe
        to call (from the watcher thread) while requests are in flight;
        each request keeps the snapshot it bound at entry. ``None``
        un-publishes: later requests refuse with NoSnapshotError (the
        fleet's quarantine-rollback path uses this rather than answer
        ahead of a rolled-back fence)."""
        self._snap = snapshot

    @property
    def snapshot(self) -> ServableSnapshot:
        snap = self._snap
        if snap is None:
            raise NoSnapshotError(
                "no servable snapshot published yet — has the trainer "
                "saved (and the watcher polled) at least once?")
        return snap

    # -- request plumbing --------------------------------------------------

    def _done(self, op: str, t0: float, rows: int) -> None:
        dt = time.perf_counter() - t0
        self.latency.add(dt)
        with self._count_lock:
            self.requests += 1
            self.rows_served += rows
        _emit_metric(self.recorder, "inc", "serve.requests", 1, op=op)
        _emit_metric(self.recorder, "inc", "serve.rows", max(rows, 0))
        _emit_metric(self.recorder, "observe", "serve.request_seconds", dt,
                     op=op)

    # -- query surface -----------------------------------------------------

    def pull(self, table: str, ids) -> tuple[int, np.ndarray]:
        """Batched pull-by-id. Returns ``(step, values)`` — the step tags
        which publish answered, so a client can reason about freshness."""
        t0 = time.perf_counter()
        if self._coalescer is not None:
            return self._coalescer.submit(
                "pull", {"table": table, "ids": ids}, t0)
        snap = self.snapshot  # bound ONCE: in-flight work survives swaps
        out = snap.lookup(table, ids)
        self._done("pull", t0, int(np.asarray(ids).size))
        return snap.step, out

    def score_linear(self, feat_ids, feat_vals, *, table: str = "weights",
                     link: str = "sigmoid") -> tuple[int, np.ndarray]:
        """Sparse linear model scores (logreg ``link="sigmoid"``, PA /
        raw margin ``link="none"``) — the serving twin of
        ``predict_proba_host``: column 0 of the pulled rows is the
        weight for every optimizer, padding ids contribute 0."""
        t0 = time.perf_counter()
        if self._coalescer is not None:
            return self._coalescer.submit(
                "score", {"feat_ids": feat_ids, "feat_vals": feat_vals,
                          "table": table, "link": link}, t0)
        snap = self.snapshot
        step, out, rows = self._score_impl(snap, feat_ids, feat_vals,
                                           table, link)
        self._done("score", t0, rows)
        return step, out

    def _score_impl(self, snap, feat_ids, feat_vals, table, link,
                    rows=None):
        """Core score compute. ``rows`` (pre-gathered weight rows for
        the flattened ids, from a batch's merged gather) skips the solo
        lookup — values are bit-identical either way."""
        feat_ids = np.asarray(feat_ids, np.int64)
        feat_vals = np.asarray(feat_vals)
        if rows is None:
            rows = snap.lookup(table, feat_ids.reshape(-1))
        w = rows[:, 0].reshape(feat_ids.shape)
        logit = np.sum(w * feat_vals, axis=-1)
        out = 1.0 / (1.0 + np.exp(-logit)) if link == "sigmoid" else logit
        return snap.step, out, int(feat_ids.size)

    def topk(self, users, k: int = 10, *, item_table: str = "item_factors",
             user_leaf: int = 0) -> tuple[int, np.ndarray, np.ndarray]:
        """MF recommendation head: top-``k`` items per user by dot
        product of the snapshot's exported user factors (``ls::<leaf>``,
        logical user order — the Trainer checkpoint path's form) against
        the item table. Returns ``(step, item_ids (U, k), scores (U, k))``.
        """
        t0 = time.perf_counter()
        if self._coalescer is not None:
            return self._coalescer.submit(
                "topk", {"users": users, "k": k,
                         "item_table": item_table,
                         "user_leaf": user_leaf}, t0)
        snap = self.snapshot
        step, items, scores, rows = self._topk_impl(
            snap, users, k, item_table, user_leaf)
        self._done("topk", t0, rows)
        return step, items, scores

    @staticmethod
    def _topk_validate(snap, users, k, item_table, user_leaf):
        """Shared topk argument gate (solo and batched paths): returns
        ``(users int64, factors)`` or raises exactly like the solo
        path always has."""
        if k < 1:
            # argpartition on k<=0 returns arbitrary columns claiming
            # ok — loud refusal, like negative user ids and raw ls.
            raise ValueError(f"k must be >= 1, got {k}")
        if snap.local_state_format != "exported":
            raise ValueError(
                "topk needs user factors in the EXPORTED (logical-order) "
                f"local-state form; snapshot step {snap.step} stores "
                f"{snap.local_state_format!r} — checkpoint through the "
                "Trainer path")
        if user_leaf >= len(snap.local_state):
            raise ValueError(
                f"snapshot step {snap.step} has {len(snap.local_state)} "
                f"local-state leaves, no leaf {user_leaf}")
        users = np.asarray(users, np.int64)
        factors = snap.local_state[user_leaf]
        if users.size and (int(users.min(initial=0)) < 0
                           or int(users.max(initial=-1))
                           >= factors.shape[0]):
            # No negative-index wraparound: serving user NU-1's items for
            # user -1 would be silently wrong data, not an error.
            raise IndexError(
                f"user ids must be in [0, {factors.shape[0]}); got "
                f"[{int(users.min())}, {int(users.max())}]")
        return users, factors

    def _topk_impl(self, snap, users, k, item_table, user_leaf):
        users, factors = self._topk_validate(snap, users, k, item_table,
                                             user_leaf)
        p = factors[users]  # (U, rank)
        # materialize(): the ONE sanctioned whole-table densification —
        # a no-op for plain maps, the cached dense form for DeltaView
        # overlays (fps_tpu/serve/snapshot.py; FPS010 allowlist seam).
        q = materialize(snap.table(item_table))  # (I, rank)
        scores = p @ q.T  # (U, I) — q stays the mapped pages
        items, out = self._topk_select(scores, k)
        return snap.step, items, out, int(users.size) * items.shape[-1]

    @staticmethod
    def _topk_select(scores, k):
        """Row-wise top-k selection — argpartition + exact ordering of
        the head. Row-independent, so selecting over a BATCH of stacked
        user blocks is bit-identical to per-block selection."""
        k = min(k, scores.shape[1])
        top = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        order = np.argsort(
            -np.take_along_axis(scores, top, axis=1), axis=1)
        items = np.take_along_axis(top, order, axis=1)
        return items, np.take_along_axis(scores, items, axis=1)

    # -- batched execution (the coalescer and multi() core) ----------------

    def multi(self, calls) -> list:
        """Execute ``calls`` — a list of ``(kind, payload)`` with kind in
        ``pull|score|topk|stats`` and payload the op's keyword dict — as ONE
        batch bound to ONE snapshot: every sub-request answers from the
        same generation, and same-table lookups merge into one
        fancy-index gather (:meth:`_run_batch`). Returns a result list
        aligned with ``calls``; a failed sub-call's slot holds its
        EXCEPTION (callers map it per-item — siblings are unaffected).
        Raises :class:`NoSnapshotError` only when nothing is published
        at all."""
        t0 = time.perf_counter()
        snap = self.snapshot
        results, rows = self._run_batch(snap, list(calls))
        self._note_batch(len(results))
        for (kind, _payload), r, rw in zip(calls, results, rows):
            if not isinstance(r, BaseException):
                self._done(kind, t0, rw)
        return results

    def _run_solo(self, kind: str, payload: dict, t0: float):
        """Un-coalesced execution of one parsed call (the coalescer's
        bounded-queue overflow path)."""
        snap = self.snapshot
        results, rows = self._run_batch(snap, [(kind, payload)])
        if isinstance(results[0], BaseException):
            raise results[0]
        self._done(kind, t0, rows[0])
        return results[0]

    def _execute_entries(self, entries) -> None:
        """Run one coalesced batch and wake every waiter. NEVER raises:
        a batch-wide failure (no snapshot, internal error) lands on each
        entry's ``error`` slot instead — a parked handler thread must
        always wake."""
        try:
            snap = self.snapshot
            results, rows = self._run_batch(
                snap, [(en.kind, en.payload) for en in entries])
        except BaseException as e:  # noqa: BLE001 — waiters must wake
            for en in entries:
                en.error = e
                en.event.set()
            return
        self._note_batch(len(entries))
        for en, r, rw in zip(entries, results, rows):
            if isinstance(r, BaseException):
                en.error = r
            else:
                en.result = r
                self._done(en.kind, en.t0, rw)
            en.event.set()

    def _note_batch(self, n: int) -> None:
        with self._count_lock:
            self.batches += 1
            self.batched_requests += n
        _emit_metric(self.recorder, "inc", "serve.batches", 1)
        _emit_metric(self.recorder, "observe", "serve.batch_size",
                     float(n))

    def _run_batch(self, snap, calls):
        """The merged-gather executor: validate every call, group
        same-table pull/score id sets into ONE concatenated fancy-index
        gather each, group same-(table, leaf, k) topk user sets into
        ONE stacked matmul + row-wise selection each, then split results
        back per call. Per-call results are bit-identical to the solo
        paths (same lookup contract, same row-independent selection);
        per-call FAILURES (bad ids, unknown tables) are validated before
        any group executes, so one bad request never poisons its batch.

        Returns ``(results, rows)`` aligned with ``calls`` — each result
        an op tuple or the exception that call would have raised solo.
        """
        n = len(calls)
        results: list = [None] * n
        rows_count = [0] * n
        gathers: dict = {}   # table -> [parsed entry]
        matmuls: dict = {}   # (item_table, leaf, k) -> [(i, users)]
        for i, (kind, payload) in enumerate(calls):
            try:
                if kind == "pull":
                    table = payload["table"]
                    ids = snap.check_ids(table, payload["ids"])
                    gathers.setdefault(table, []).append(
                        ("pull", i, ids))
                elif kind == "score":
                    table = payload.get("table", "weights")
                    feat_ids = snap.check_ids(table, payload["feat_ids"])
                    feat_vals = np.asarray(payload["feat_vals"])
                    gathers.setdefault(table, []).append(
                        ("score", i, feat_ids, feat_vals,
                         payload.get("link", "sigmoid")))
                elif kind == "topk":
                    k = int(payload.get("k", 10))
                    item_table = payload.get("item_table", "item_factors")
                    leaf = int(payload.get("user_leaf", 0))
                    users, _factors = self._topk_validate(
                        snap, payload["users"], k, item_table, leaf)
                    if users.ndim != 1:
                        raise ValueError(
                            f"topk users must be 1-D, got shape "
                            f"{users.shape}")
                    matmuls.setdefault((item_table, leaf, k), []).append(
                        (i, users))
                elif kind == "stats":
                    # No table work: answer inline so a mixed multi
                    # frame can carry health probes for free.
                    results[i] = self.stats()
                else:
                    raise ValueError(f"unknown op {kind!r}")
            except Exception as e:  # noqa: BLE001 — per-call verdicts
                results[i] = e
        for table, entries in gathers.items():
            flats = [e[2].reshape(-1) for e in entries]
            offsets = np.cumsum([0] + [f.size for f in flats])
            cat = flats[0] if len(flats) == 1 else np.concatenate(flats)
            rows = snap.lookup(table, cat)  # ONE gather for the group
            for j, e in enumerate(entries):
                seg = rows[offsets[j]:offsets[j + 1]]
                if e[0] == "pull":
                    _, i, ids = e
                    results[i] = (snap.step,
                                  seg.reshape(ids.shape + rows.shape[1:]))
                    rows_count[i] = int(ids.size)
                else:
                    _, i, feat_ids, feat_vals, link = e
                    try:
                        step, out, rc = self._score_impl(
                            snap, feat_ids, feat_vals, table, link,
                            rows=seg)
                        results[i] = (step, out)
                        rows_count[i] = rc
                    except Exception as err:  # noqa: BLE001
                        results[i] = err
        for (item_table, leaf, k), entries in matmuls.items():
            factors = snap.local_state[leaf]
            flats = [u for _i, u in entries]
            offsets = np.cumsum([0] + [u.size for u in flats])
            cat = flats[0] if len(flats) == 1 else np.concatenate(flats)
            p = factors[cat]
            q = materialize(snap.table(item_table))
            scores = p @ q.T  # ONE stacked matmul for the group
            items, sc = self._topk_select(scores, k)
            for j, (i, users) in enumerate(entries):
                results[i] = (snap.step, items[offsets[j]:offsets[j + 1]],
                              sc[offsets[j]:offsets[j + 1]])
                rows_count[i] = int(users.size) * items.shape[-1]
        return results, rows_count

    # -- digest ------------------------------------------------------------

    def latency_s(self) -> dict[str, float] | None:
        """``{"p50": s, "p99": s}`` over the retained request window."""
        return self.latency.quantiles()

    def stats(self) -> dict:
        snap = self._snap
        lat = self.latency_s() or {}
        return {
            "step": None if snap is None else snap.step,
            "tables": sorted(snap.tables) if snap is not None else [],
            "requests": self.requests,
            "rows_served": self.rows_served,
            "latency_p50_s": lat.get("p50"),
            "latency_p99_s": lat.get("p99"),
        }
