"""One published snapshot, opened for reading: verify, map, look up.

:class:`ServableSnapshot` is the read-path's unit of publication — one
``ckpt_*.npz`` file that has passed the full CRC integrity pass
(:func:`fps_tpu.core.snapshot_format.verify_snapshot_file`) and whose
array entries are mapped read-only into this process
(:func:`~fps_tpu.core.snapshot_format.map_snapshot_arrays`): ``np.memmap``
views straight onto the member bytes, no decompression, no copy, no
resident memory until rows are touched. Opening a multi-GB snapshot costs
header parsing plus one CRC pass; *swapping* a server to an already-open
snapshot is a pointer flip whose cost is independent of table size.

Lifetime: the maps address the published file's INODE. The checkpoint
writer only ever publishes via atomic rename (a fresh inode per save), so
a mapped snapshot can never change underneath a reader; retention GC or a
``*.corrupt`` quarantine merely unlinks the NAME — in-flight reads on the
old map stay valid until the last reference drops. That property is what
makes the serving hot-swap safe without any reader/writer locking.

jax-free (stdlib + numpy): a serving process needs no accelerator
runtime. Import through the real package or a stub root
(``tools/serve.py``) — nothing here touches the training plane.
"""

from __future__ import annotations

import os
import time

import numpy as np

from fps_tpu.core import snapshot_format as fmt

__all__ = ["ServableSnapshot", "SnapshotRejected", "DeltaView",
           "materialize"]


class SnapshotRejected(RuntimeError):
    """A snapshot failed integrity verification and was not opened.

    Raised by :meth:`ServableSnapshot.open` — the serving analog of the
    training plane's ``SnapshotCorruptionError``, separate so the serving
    tier never needs the jax-laden resilience module."""


class DeltaView:
    """A read-only row-overlay view: ``base`` (typically a zero-copy
    snapshot map) patched at ``ids`` (sorted, unique) with ``rows``.

    The delta-aware incremental hot-swap's data structure: applying a
    delta to a served table costs O(touched rows) of memory and leaves
    the multi-GB base mapped exactly as it was — no re-open, no copy.
    Lookups fancy-index like an ndarray (``view[ids]``), and
    ``np.asarray(view)`` materializes the patched table for whole-table
    consumers (MF top-k). The warm-row cache reuses the same structure
    with ``rows`` equal to the base's values: hot lookups then come from
    a resident contiguous buffer instead of faulting mapped pages.

    Immutable after construction; thread-safe like the plain maps.
    """

    __slots__ = ("base", "ids", "rows", "_dense")

    def __init__(self, base, ids, rows):
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows)
        if ids.ndim != 1 or len(ids) != len(rows):
            raise ValueError("ids must be 1-D and match rows")
        if len(ids) and (np.any(np.diff(ids) <= 0) or ids[0] < 0
                         or ids[-1] >= base.shape[0]):
            raise ValueError("ids must be sorted, unique, in range")
        self.base = base
        self.ids = ids
        self.rows = rows
        self._dense = None  # lazy whole-table materialization (cached)

    @property
    def shape(self):
        return self.base.shape

    @property
    def dtype(self):
        return self.base.dtype

    @property
    def ndim(self):
        return self.base.ndim

    def __len__(self):
        return len(self.base)

    def __getitem__(self, idx):
        idx = np.asarray(idx)
        scalar = idx.ndim == 0
        if scalar:
            idx = idx.reshape(1)
        out = np.asarray(self.base[idx])
        if len(self.ids):
            pos = np.searchsorted(self.ids, idx)
            pos_c = np.minimum(pos, len(self.ids) - 1)
            hit = self.ids[pos_c] == idx
            if np.any(hit):
                out = np.array(out, copy=True)
                out[hit] = self.rows[pos_c[hit]]
        return out[0] if scalar else out

    def __array__(self, dtype=None, copy=None):
        # Cached: whole-table consumers (MF top-k scores every request
        # against np.asarray(table)) must not pay an O(table) copy per
        # request once a delta/warm overlay is installed. One overlay is
        # immutable, so the materialization is too; a racing double
        # compute is benign (last write wins, identical bytes).
        if self._dense is None:
            mat = np.array(self.base, copy=True)
            if len(self.ids):
                mat[self.ids] = self.rows
            mat.setflags(write=False)
            self._dense = mat
        mat = self._dense
        return mat.astype(dtype) if dtype is not None else mat

    @property
    def overlay_rows(self) -> int:
        return int(len(self.ids))


def materialize(table):
    """The ONE sanctioned whole-table densification seam (lint rule
    FPS010 allowlists exactly this and :meth:`DeltaView.__array__`).

    Plain ndarray/memmap tables return AS-IS — zero copy, zero
    allocation; whole-table consumers (MF top-k's matmul) read the
    mapped pages directly. :class:`DeltaView` overlays return their
    CACHED dense form (one O(table) copy per overlay lifetime, amortized
    across every request that binds the snapshot). Hot-path serve code
    must route whole-table access through here instead of
    ``np.asarray``/``np.array``/``.copy()`` — the static guard that
    keeps zero-copy zero-copy."""
    if isinstance(table, DeltaView):
        return table.__array__()
    return table


def _merge_overlay(base_ids, base_rows, ids, rows):
    """Fold one more delta's (ids, rows) onto an existing overlay —
    later wins on collisions. All inputs sorted-unique; output too."""
    if not len(base_ids):
        return ids, rows
    if not len(ids):
        return base_ids, base_rows
    keep = ~np.isin(base_ids, ids)
    merged_ids = np.concatenate([base_ids[keep], ids])
    merged_rows = np.concatenate([base_rows[keep], rows])
    order = np.argsort(merged_ids, kind="stable")
    return merged_ids[order], merged_rows[order]


def _overlay(value, ids, rows):
    """Patch ``value`` (ndarray map or DeltaView) at ``ids`` → DeltaView
    over the ORIGINAL base (chained overlays fold flat, never stack)."""
    ids = np.asarray(ids, np.int64)
    if isinstance(value, DeltaView):
        mids, mrows = _merge_overlay(value.ids, value.rows, ids, rows)
        return DeltaView(value.base, mids, mrows)
    return DeltaView(value, ids, rows)


class ServableSnapshot:
    """A CRC-verified, read-only-mapped snapshot.

    Construct via :meth:`open` (which verifies first — a torn or
    bit-rotted file raises :class:`SnapshotRejected` before anything is
    mapped). Tables are exposed in LOGICAL id order, padding stripped —
    exactly as the checkpoint writer serializes them — so a served row
    lookup is a plain axis-0 index, with no owner-major physical mapping
    and no dependence on the training mesh shape.

    Thread-safety: instances are immutable after ``open`` (plain reads of
    read-only maps); any number of request threads may share one.
    """

    def __init__(self, step: int, path: str, tables: dict,
                 local_state: list, local_state_format: str, *,
                 verify_seconds: float = 0.0, src_id=None,
                 chain_len: int = 1, warm_rows: int = 0,
                 pod_epoch: int | None = None):
        self.step = int(step)
        self.path = path
        self.tables = tables  # {name: (num_ids, dim) read-only array}
        self.local_state = local_state  # exported ls:: leaves, in order
        self.local_state_format = local_state_format
        self.verify_seconds = verify_seconds
        # (st_ino, st_mtime_ns) of the mapped file — the identity the
        # watcher compares so an atomic re-publish of the SAME step
        # (quarantine → rollback replay) is seen as a new snapshot.
        self.src_id = src_id
        # Delta-chain provenance: how many publications (full + deltas)
        # describe this state, and how many warm-cache rows were
        # admitted (DeltaView overlays with base-equal values).
        self.chain_len = chain_len
        self.warm_rows = warm_rows
        # The writer's fencing epoch (meta::pod_epoch, pod runs only) —
        # the incremental swap refuses a delta carrying an OLDER epoch
        # than the snapshot it extends (a stale zombie's publish).
        self.pod_epoch = pod_epoch

    @classmethod
    def open(cls, path: str, *, step: int | None = None,
             verify: bool = True) -> "ServableSnapshot":
        """Verify ``path`` then map it. ``step`` defaults to the value
        parsed from the filename; ``verify=False`` skips the CRC pass
        (only for callers that just verified the same inode)."""
        if step is None:
            m = fmt.SNAPSHOT_RE.fullmatch(os.path.basename(path))
            if not m:
                raise ValueError(
                    f"{path!r} does not match the snapshot naming contract "
                    f"({fmt.SNAPSHOT_RE.pattern})")
            step = int(m.group(1))
        t0 = time.perf_counter()
        if verify:
            ok, reason = fmt.verify_snapshot_file(path)
            if not ok:
                if reason == fmt.NO_SUCH_FILE:
                    # The candidate vanished between the caller's scan
                    # and this open (retention sweep / quarantine rename
                    # racing the poll loop): "gone, retry next poll" —
                    # never a corruption verdict.
                    raise FileNotFoundError(path)
                raise SnapshotRejected(
                    f"snapshot step {step} at {path}: {reason}")
        verify_s = time.perf_counter() - t0
        try:
            st = os.stat(path)
            arrays = fmt.map_snapshot_arrays(path)
            ls_format, pod_epoch = _meta_tags(path)
        except FileNotFoundError:
            raise
        except fmt.IO_ERRORS as e:
            # verify→map is not atomic against a concurrent quarantine
            # rename; surface the race as a rejection, not a crash.
            raise SnapshotRejected(
                f"snapshot step {step} at {path}: vanished or unreadable "
                f"between verify and map ({e!r})") from e
        tables = {k[len(fmt.TABLE_PREFIX):]: v for k, v in arrays.items()
                  if k.startswith(fmt.TABLE_PREFIX)}
        ls: list = []
        while fmt.LS_PREFIX + str(len(ls)) in arrays:
            ls.append(arrays[fmt.LS_PREFIX + str(len(ls))])
        return cls(step, path, tables, ls, ls_format,
                   verify_seconds=verify_s,
                   src_id=(st.st_ino, st.st_mtime_ns),
                   pod_epoch=pod_epoch)

    # -- delta chains ------------------------------------------------------

    @classmethod
    def open_chain(cls, directory: str, step: int, *,
                   verify: bool = True) -> "ServableSnapshot":
        """Open publication ``step`` resolving its delta chain: the base
        FULL is zero-copy mapped exactly like :meth:`open`, every delta
        link (O(touched rows) by construction) is loaded into memory and
        folded into :class:`DeltaView` overlays. The whole chain is
        CRC/link/epoch-verified first — a chain through a torn, missing,
        or ``*.corrupt``-quarantined base refuses with
        :class:`SnapshotRejected` (or :class:`FileNotFoundError` when
        the head itself vanished mid-poll)."""
        pubs = fmt.publications(directory)
        pub = pubs.get(step)
        if pub is None:
            raise FileNotFoundError(fmt.snapshot_path(directory, step))
        if verify:
            ok, reason, failing = fmt.verify_chain(directory, step,
                                                   pubs=pubs)
            if not ok:
                if (failing == step and reason is not None
                        and reason.endswith(fmt.NO_SUCH_FILE)):
                    # The HEAD itself vanished between the caller's scan
                    # and the verify pass (retention sweep racing the
                    # poll): gone, not corrupt.
                    raise FileNotFoundError(pub.path)
                raise SnapshotRejected(
                    f"chain for step {step} under {directory}: {reason}")
        try:
            members = fmt.chain_members(pubs, step)
        except fmt.ChainError as e:
            # verify=False callers reach here with a broken chain (a
            # swept/missing base): a rejection, never an escaped
            # ChainError — poll loops are documented not to raise.
            raise SnapshotRejected(
                f"chain for step {step} under {directory}: {e}") from e
        t0 = time.perf_counter()
        snap = cls.open(members[0].path, step=members[0].step,
                        verify=False)  # chain verify above covered it
        for link in members[1:]:
            snap = snap.with_delta(link.path, verify=False)
        snap.verify_seconds = time.perf_counter() - t0
        return snap

    def with_delta(self, delta_file: str, *,
                   verify: bool = True) -> "ServableSnapshot":
        """The INCREMENTAL hot-swap: a new snapshot describing
        ``delta_file``'s step by patching this snapshot's (still-mapped)
        tables with the delta's touched rows — the world is not
        re-opened, re-verified, or copied; cost is O(touched rows).

        The delta must chain from exactly this snapshot's step
        (``meta::base_step``); anything else refuses loudly."""
        t0 = time.perf_counter()
        if verify:
            ok, reason = fmt.verify_snapshot_file(delta_file)
            if not ok:
                if reason == fmt.NO_SUCH_FILE:
                    raise FileNotFoundError(delta_file)
                raise SnapshotRejected(f"delta {delta_file}: {reason}")
        try:
            delta = fmt.read_delta_arrays(delta_file)
        except FileNotFoundError:
            raise
        except fmt.IO_ERRORS as e:
            raise SnapshotRejected(
                f"delta {delta_file}: vanished or unreadable between "
                f"verify and read ({e!r})") from e
        base_step = delta.get(fmt.BASE_STEP_KEY)
        if base_step is None or int(base_step) != self.step:
            raise SnapshotRejected(
                f"delta {delta_file} chains from step "
                f"{None if base_step is None else int(base_step)}, not "
                f"the served step {self.step}")
        epoch = delta.get(fmt.POD_EPOCH_KEY)
        epoch = None if epoch is None else int(epoch)
        if (epoch is not None and self.pod_epoch is not None
                and epoch < self.pod_epoch):
            # The read-side half of the pod fence: an epoch-stale delta
            # is a zombie writer's publish — never extend through it.
            raise SnapshotRejected(
                f"delta {delta_file}: fencing epoch {epoch} is behind "
                f"the served snapshot's epoch {self.pod_epoch}")
        m = fmt.DELTA_RE.fullmatch(os.path.basename(delta_file))
        if not m:
            raise SnapshotRejected(
                f"{delta_file!r} does not match the delta naming "
                f"contract ({fmt.DELTA_RE.pattern})")
        step = int(m.group(1))
        tables = dict(self.tables)
        ls = list(self.local_state)
        ls_format = self.local_state_format
        for k, v in delta.items():
            if (k.startswith(fmt.DELTA_IDS_PREFIX)
                    or k == fmt.BASE_STEP_KEY):
                continue
            if k.startswith(fmt.DELTA_ROWS_PREFIX):
                key = k[len(fmt.DELTA_ROWS_PREFIX):]
                ids = delta[fmt.DELTA_IDS_PREFIX + key]
                if key.startswith(fmt.TABLE_PREFIX):
                    name = key[len(fmt.TABLE_PREFIX):]
                    if name not in tables:
                        raise SnapshotRejected(
                            f"delta {delta_file} patches unknown table "
                            f"{name!r}")
                    tables[name] = _overlay(tables[name], ids, v)
                elif key.startswith(fmt.LS_PREFIX):
                    i = int(key[len(fmt.LS_PREFIX):])
                    if i >= len(ls):
                        raise SnapshotRejected(
                            f"delta {delta_file} patches unknown "
                            f"local-state leaf {i}")
                    ls[i] = _overlay(ls[i], ids, v)
                # fold:: state is training-plane-only — not served.
            elif k.startswith(fmt.TABLE_PREFIX):
                tables[k[len(fmt.TABLE_PREFIX):]] = v  # full replacement
            elif k.startswith(fmt.LS_PREFIX):
                i = int(k[len(fmt.LS_PREFIX):])
                while len(ls) <= i:
                    ls.append(None)
                ls[i] = v
            elif k == "meta" + fmt.SEP + "ls_format":
                ls_format = str(v)
        snap = ServableSnapshot(
            step, delta_file, tables, ls, ls_format,
            verify_seconds=time.perf_counter() - t0,
            src_id=_stat_id(delta_file), chain_len=self.chain_len + 1,
            warm_rows=self.warm_rows,
            pod_epoch=self.pod_epoch if epoch is None else epoch)
        return snap

    def warmed(self, ids_by_table: dict) -> "ServableSnapshot":
        """Warm-row cache admission: materialize the given rows (the
        hot-tier frequency ranking's head) into resident overlay buffers
        so hot lookups stop faulting mapped pages. Values are the
        snapshot's own — semantics are bit-identical, only residency
        changes. Unknown tables / out-of-range ids are clipped silently
        (the ranking may predate a re-shape)."""
        tables = dict(self.tables)
        warm = self.warm_rows
        for name, ids in ids_by_table.items():
            cur = tables.get(name)
            if cur is None:
                continue
            ids = np.unique(np.asarray(ids, np.int64).reshape(-1))
            ids = ids[(ids >= 0) & (ids < cur.shape[0])]
            if not len(ids):
                continue
            rows = np.ascontiguousarray(cur[ids])
            tables[name] = _overlay(cur, ids, rows)
            warm += int(len(ids))
        snap = ServableSnapshot(
            self.step, self.path, tables, list(self.local_state),
            self.local_state_format, verify_seconds=self.verify_seconds,
            src_id=self.src_id, chain_len=self.chain_len, warm_rows=warm,
            pod_epoch=self.pod_epoch)
        return snap

    # -- lookups -----------------------------------------------------------

    def table(self, name: str) -> np.ndarray:
        try:
            return self.tables[name]
        except KeyError:
            raise KeyError(
                f"snapshot step {self.step} has no table {name!r} "
                f"(tables: {sorted(self.tables)})") from None

    def check_ids(self, name: str, ids) -> np.ndarray:
        """Validate ``ids`` against table ``name`` — same parse and
        errors as :meth:`lookup`, WITHOUT the gather. The batched
        request path pre-validates every sub-request through here so a
        bad one fails alone instead of poisoning its merged gather.
        Returns the ids as int64."""
        t = self.table(name)
        ids = np.asarray(ids, np.int64)
        if ids.size and ids.max(initial=-1) >= t.shape[0]:
            raise IndexError(
                f"table {name!r}: id {int(ids.max())} out of range "
                f"({t.shape[0]} rows)")
        if ids.size and ids.min(initial=0) < -1:
            # Only -1 is the padding sentinel; any other negative is a
            # client bug that must not silently read as a zero row.
            raise IndexError(
                f"table {name!r}: id {int(ids.min())} below the -1 "
                f"padding sentinel")
        return ids

    def lookup(self, name: str, ids) -> np.ndarray:
        """Batched pull-by-id: rows ``ids`` of table ``name`` (logical id
        order). Padding ids (``-1``) read as zero rows, matching the
        training plane's dropped-row contract; out-of-range ids — above
        the table or below the ``-1`` sentinel — raise."""
        t = self.table(name)
        ids = self.check_ids(name, ids)
        live = ids >= 0
        out = t[np.where(live, ids, 0)]
        if not live.all():
            out = np.where(live[..., None] if out.ndim > ids.ndim
                           else live, out, 0).astype(t.dtype, copy=False)
        return out

    def manifest(self) -> dict:
        """Shape/dtype summary (no data touched) — the publish manifest
        the CLI and the obs digest surface."""
        return {
            "step": self.step,
            "path": self.path,
            "tables": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                       for k, v in sorted(self.tables.items())},
            "local_state": [{"shape": list(v.shape), "dtype": str(v.dtype)}
                            for v in self.local_state],
            "local_state_format": self.local_state_format,
        }


def _stat_id(path: str):
    """(st_ino, st_mtime_ns) or None — the watcher's identity tuple."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns)


def _meta_tags(path: str) -> tuple[str, int | None]:
    """``(ls_format, pod_epoch)`` meta tags of a snapshot (``"raw"`` /
    ``None`` when absent) — one numpy lazy-member read (only these
    entries' bytes)."""
    key = "meta" + fmt.SEP + "ls_format"
    with np.load(path) as z:
        ls_format = str(z[key]) if key in z.files else "raw"
        epoch = (int(z[fmt.POD_EPOCH_KEY])
                 if fmt.POD_EPOCH_KEY in z.files else None)
    return ls_format, epoch
