"""One published snapshot, opened for reading: verify, map, look up.

:class:`ServableSnapshot` is the read-path's unit of publication — one
``ckpt_*.npz`` file that has passed the full CRC integrity pass
(:func:`fps_tpu.core.snapshot_format.verify_snapshot_file`) and whose
array entries are mapped read-only into this process
(:func:`~fps_tpu.core.snapshot_format.map_snapshot_arrays`): ``np.memmap``
views straight onto the member bytes, no decompression, no copy, no
resident memory until rows are touched. Opening a multi-GB snapshot costs
header parsing plus one CRC pass; *swapping* a server to an already-open
snapshot is a pointer flip whose cost is independent of table size.

Lifetime: the maps address the published file's INODE. The checkpoint
writer only ever publishes via atomic rename (a fresh inode per save), so
a mapped snapshot can never change underneath a reader; retention GC or a
``*.corrupt`` quarantine merely unlinks the NAME — in-flight reads on the
old map stay valid until the last reference drops. That property is what
makes the serving hot-swap safe without any reader/writer locking.

jax-free (stdlib + numpy): a serving process needs no accelerator
runtime. Import through the real package or a stub root
(``tools/serve.py``) — nothing here touches the training plane.
"""

from __future__ import annotations

import os
import time

import numpy as np

from fps_tpu.core import snapshot_format as fmt

__all__ = ["ServableSnapshot", "SnapshotRejected"]


class SnapshotRejected(RuntimeError):
    """A snapshot failed integrity verification and was not opened.

    Raised by :meth:`ServableSnapshot.open` — the serving analog of the
    training plane's ``SnapshotCorruptionError``, separate so the serving
    tier never needs the jax-laden resilience module."""


class ServableSnapshot:
    """A CRC-verified, read-only-mapped snapshot.

    Construct via :meth:`open` (which verifies first — a torn or
    bit-rotted file raises :class:`SnapshotRejected` before anything is
    mapped). Tables are exposed in LOGICAL id order, padding stripped —
    exactly as the checkpoint writer serializes them — so a served row
    lookup is a plain axis-0 index, with no owner-major physical mapping
    and no dependence on the training mesh shape.

    Thread-safety: instances are immutable after ``open`` (plain reads of
    read-only maps); any number of request threads may share one.
    """

    def __init__(self, step: int, path: str, tables: dict,
                 local_state: list, local_state_format: str, *,
                 verify_seconds: float = 0.0, src_id=None):
        self.step = int(step)
        self.path = path
        self.tables = tables  # {name: (num_ids, dim) read-only array}
        self.local_state = local_state  # exported ls:: leaves, in order
        self.local_state_format = local_state_format
        self.verify_seconds = verify_seconds
        # (st_ino, st_mtime_ns) of the mapped file — the identity the
        # watcher compares so an atomic re-publish of the SAME step
        # (quarantine → rollback replay) is seen as a new snapshot.
        self.src_id = src_id

    @classmethod
    def open(cls, path: str, *, step: int | None = None,
             verify: bool = True) -> "ServableSnapshot":
        """Verify ``path`` then map it. ``step`` defaults to the value
        parsed from the filename; ``verify=False`` skips the CRC pass
        (only for callers that just verified the same inode)."""
        if step is None:
            m = fmt.SNAPSHOT_RE.fullmatch(os.path.basename(path))
            if not m:
                raise ValueError(
                    f"{path!r} does not match the snapshot naming contract "
                    f"({fmt.SNAPSHOT_RE.pattern})")
            step = int(m.group(1))
        t0 = time.perf_counter()
        if verify:
            ok, reason = fmt.verify_snapshot_file(path)
            if not ok:
                raise SnapshotRejected(
                    f"snapshot step {step} at {path}: {reason}")
        verify_s = time.perf_counter() - t0
        try:
            st = os.stat(path)
            arrays = fmt.map_snapshot_arrays(path)
            ls_format = _ls_format(path)
        except FileNotFoundError:
            raise
        except fmt.IO_ERRORS as e:
            # verify→map is not atomic against a concurrent quarantine
            # rename; surface the race as a rejection, not a crash.
            raise SnapshotRejected(
                f"snapshot step {step} at {path}: vanished or unreadable "
                f"between verify and map ({e!r})") from e
        tables = {k[len(fmt.TABLE_PREFIX):]: v for k, v in arrays.items()
                  if k.startswith(fmt.TABLE_PREFIX)}
        ls: list = []
        while fmt.LS_PREFIX + str(len(ls)) in arrays:
            ls.append(arrays[fmt.LS_PREFIX + str(len(ls))])
        return cls(step, path, tables, ls, ls_format,
                   verify_seconds=verify_s,
                   src_id=(st.st_ino, st.st_mtime_ns))

    # -- lookups -----------------------------------------------------------

    def table(self, name: str) -> np.ndarray:
        try:
            return self.tables[name]
        except KeyError:
            raise KeyError(
                f"snapshot step {self.step} has no table {name!r} "
                f"(tables: {sorted(self.tables)})") from None

    def lookup(self, name: str, ids) -> np.ndarray:
        """Batched pull-by-id: rows ``ids`` of table ``name`` (logical id
        order). Padding ids (``-1``) read as zero rows, matching the
        training plane's dropped-row contract; out-of-range ids — above
        the table or below the ``-1`` sentinel — raise."""
        t = self.table(name)
        ids = np.asarray(ids, np.int64)
        if ids.size and ids.max(initial=-1) >= t.shape[0]:
            raise IndexError(
                f"table {name!r}: id {int(ids.max())} out of range "
                f"({t.shape[0]} rows)")
        if ids.size and ids.min(initial=0) < -1:
            # Only -1 is the padding sentinel; any other negative is a
            # client bug that must not silently read as a zero row.
            raise IndexError(
                f"table {name!r}: id {int(ids.min())} below the -1 "
                f"padding sentinel")
        live = ids >= 0
        out = t[np.where(live, ids, 0)]
        if not live.all():
            out = np.where(live[..., None] if out.ndim > ids.ndim
                           else live, out, 0).astype(t.dtype, copy=False)
        return out

    def manifest(self) -> dict:
        """Shape/dtype summary (no data touched) — the publish manifest
        the CLI and the obs digest surface."""
        return {
            "step": self.step,
            "path": self.path,
            "tables": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                       for k, v in sorted(self.tables.items())},
            "local_state": [{"shape": list(v.shape), "dtype": str(v.dtype)}
                            for v in self.local_state],
            "local_state_format": self.local_state_format,
        }


def _ls_format(path: str) -> str:
    """The snapshot's ``meta::ls_format`` tag (``"raw"`` when absent) —
    read through numpy's lazy member access (only this entry's bytes)."""
    key = "meta" + fmt.SEP + "ls_format"
    with np.load(path) as z:
        return str(z[key]) if key in z.files else "raw"
