"""Line-oriented JSON over TCP: the thinnest possible wire for ReadServer.

One request per line, one response per line (both JSON objects) — the
same framing as every other artifact in this repo (journals, event logs,
bench digests), so the protocol needs no schema machinery and any
language's socket + JSON can speak it:

  {"op": "pull",  "table": "weights", "ids": [0, 5, 9]}
  {"op": "score", "feat_ids": [[...]], "feat_vals": [[...]],
   "table": "weights", "link": "sigmoid"}
  {"op": "topk",  "users": [1, 2], "k": 10, "item_table": "item_factors"}
  {"op": "stats"}

Responses carry ``"ok": true`` plus the op's payload (every data op tags
``"step"`` — the publish that answered), or ``"ok": false, "error": ...``
for malformed requests; the connection survives bad requests (a serving
endpoint must not let one typo'd client kill the socket).

This is a test/bench/demo transport, deliberately not a production
server (no TLS, no auth, no backpressure): the subsystem's contract is
the :class:`~fps_tpu.serve.server.ReadServer` surface; production fronts
would sit where :class:`TcpServe` sits.

thread-safety: one daemon thread per connection plus the acceptor
(``socketserver.ThreadingTCPServer``); all shared state lives in the
ReadServer, whose read path is lock-free by design (see its docstring).
"""

from __future__ import annotations

import json
import math
import socket
import socketserver
import threading

import numpy as np

from fps_tpu.obs.sinks import scrub_nonfinite
from fps_tpu.serve.server import NoSnapshotError, ReadServer

__all__ = ["TcpServe", "JsonlClient"]


def _py(v):
    # Non-finite floats serialize as null: json.dumps would otherwise emit
    # Python-only NaN/Infinity tokens that strict parsers reject, and a
    # published snapshot CAN hold non-finite rows (observe-mode guards
    # count them without reverting).
    if isinstance(v, np.ndarray):
        out = v.tolist()
        if v.dtype.kind == "f" and not np.isfinite(v).all():
            out = scrub_nonfinite(out)
        return out
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return None if not math.isfinite(v) else float(v)
    return v


def handle_request(server: ReadServer, req: dict) -> dict:
    """One request → one response dict (transport-independent: the TCP
    handler and the in-process client in tests both call this)."""
    if not isinstance(req, dict):
        # Valid JSON but not an object ('[1]', 'null'): still one error
        # line, never a dropped connection.
        return {"ok": False,
                "error": f"request must be a JSON object, got "
                         f"{type(req).__name__}"}
    try:
        op = req.get("op")
        if op == "pull":
            step, vals = server.pull(req["table"], req["ids"])
            return {"ok": True, "step": step, "values": _py(vals)}
        if op == "score":
            step, scores = server.score_linear(
                req["feat_ids"], req["feat_vals"],
                table=req.get("table", "weights"),
                link=req.get("link", "sigmoid"))
            return {"ok": True, "step": step, "scores": _py(scores)}
        if op == "topk":
            step, items, scores = server.topk(
                req["users"], int(req.get("k", 10)),
                item_table=req.get("item_table", "item_factors"),
                user_leaf=int(req.get("user_leaf", 0)))
            return {"ok": True, "step": step, "items": _py(items),
                    "scores": _py(scores)}
        if op == "stats":
            return {"ok": True, **server.stats()}
        return {"ok": False, "error": f"unknown op {op!r}"}
    except NoSnapshotError as e:
        return {"ok": False, "error": str(e), "retryable": True}
    except (KeyError, IndexError, TypeError, ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


class TcpServe:
    """Serve a :class:`ReadServer` on ``127.0.0.1:port`` (0 = ephemeral;
    read the bound port from :attr:`port`). ``start()`` returns
    immediately (daemon threads); ``close()`` shuts the socket down.

    thread-safety: the handler threads share only the ReadServer, whose
    read path is lock-free by design (snapshot bound once per request;
    see its docstring) — this class itself owns no mutable state past
    construction, and ``ThreadingTCPServer.shutdown`` is the only
    cross-thread call."""

    def __init__(self, server: ReadServer, *, host: str = "127.0.0.1",
                 port: int = 0):
        read_server = server

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                    except json.JSONDecodeError as e:
                        resp = {"ok": False, "error": f"bad json: {e}"}
                    else:
                        resp = handle_request(read_server, req)
                    try:
                        payload = json.dumps(resp, allow_nan=False)
                    except ValueError:
                        # Belt-and-braces: _py() nulls non-finite floats,
                        # so any stray NaN here is a protocol bug — fail
                        # the one response, not the wire contract.
                        payload = json.dumps(
                            {"ok": False,
                             "error": "non-finite value in response"})
                    self.wfile.write((payload + "\n").encode("utf-8"))
                    self.wfile.flush()

        self._tcp = socketserver.ThreadingTCPServer(
            (host, port), Handler, bind_and_activate=True)
        self._tcp.daemon_threads = True
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="fps-serve-tcp",
            daemon=True)
        self.host, self.port = self._tcp.server_address[:2]

    def start(self) -> "TcpServe":
        self._thread.start()
        return self

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


class JsonlClient:
    """Blocking client for the line protocol (tests and the CLI's
    ``--query`` mode)."""

    def __init__(self, host: str, port: int, *, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._rfile = self._sock.makefile("rb")

    def request(self, req: dict) -> dict:
        self._sock.sendall((json.dumps(req) + "\n").encode("utf-8"))
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def close(self) -> None:
        self._rfile.close()
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
