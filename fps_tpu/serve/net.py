"""TCP transport for ReadServer: framed wire only.

The wire protocol proper lives in :mod:`fps_tpu.serve.wire` (versioned
length-prefixed frames, CRC32, HELLO negotiation, the failure-aware
:class:`~fps_tpu.serve.wire.WireClient`). This module is the SERVER
side. The PR-16 dual-stack bridge (first-byte peek routing legacy
line-JSON clients into a compat loop) served its one deprecation
release and is RETIRED: every connection must open with the framed
HELLO. A legacy line-JSON peer now fails the first frame's magic/CRC
gates and gets a counted ``torn_frames`` OP_ERR + dropped connection —
loud, immediate, and impossible to half-support (``docs/serving.md``).
:class:`JsonlClient` remains as a thin compat shim over ``WireClient``
(same constructor and ``request()`` surface, framed wire underneath).

Server-side survival (the tentpole's third leg):

* **admission control** — a cost-weighted, latency-governed
  :class:`~fps_tpu.serve.admission.AdmissionController` (a ``topk``
  matmul weighs ~8x a ``pull``; a batched ``multi`` frame weighs the
  sum of its members); a request the budget cannot cover is shed with
  a retryable ``BUSY`` frame (counted as ``net.shed_requests`` — the
  shed-rate SLO in ``fps_tpu.obs.fleet`` burns on it) instead of
  queueing unboundedly. Load shedding is lost WORK, never lost
  CORRECTNESS: the client retries or degrades (``docs/STALENESS.md``).
* **deadline enforcement** — request envelopes carry the client's
  remaining budget; a request that is already dead on arrival is
  answered with a retryable ``deadline_exceeded`` response
  (``net.deadline_exceeded``) rather than executed into a void. A
  per-connection socket timeout reaps partitioned peers so a silent
  client can never pin a handler thread forever.
* **torn-frame accounting** — a frame that fails its length/CRC gates
  is counted (``net.torn_frames``), journaled, and the connection
  dropped loudly; the payload is NEVER decoded.
* **idempotent replay** — executed responses are cached per
  ``(session, req_id)`` in a BYTE-bounded LRU (``replay_cache_bytes``;
  cache cost is response-size-dependent, so an entry-count bound would
  let one big-response tenant evict a small tenant's entries at ~zero
  byte cost); a reconnecting client resending an in-flight request
  gets the cached response, not a second execution (the
  zero-duplicate-applies chaos invariant). Evictions are counted
  (``net.replay_cache_evictions``): an evicted entry's resend
  re-executes — duplicate work, never a duplicate side effect for
  these idempotent reads.

The request/response dicts (and :func:`handle_request`) are unchanged
from the line protocol — framing added integrity and liveness, not a
new schema.

thread-safety: one daemon thread per connection plus the acceptor
(``socketserver.ThreadingTCPServer``); shared state is the ReadServer
(lock-free read path by design), the replay cache and wire-stat
counters (one lock each), and the admission controller (its own lock).
"""

from __future__ import annotations

import collections
import json
import math
import socket
import socketserver
import threading
import time

import numpy as np

from fps_tpu.core.retry import net_fault_check
from fps_tpu.obs.sinks import scrub_nonfinite
from fps_tpu.serve.admission import AdmissionController
from fps_tpu.serve.server import NoSnapshotError, ReadServer
from fps_tpu.serve.watcher import _emit_event, _emit_metric
from fps_tpu.serve.wire import (CAP_BIN, CAP_CRC_LIGHT, CAP_MULTI,
                                CRC_LIGHT_THRESHOLD, FLAG_BIN,
                                OP_BUSY, OP_ERR, OP_HELLO, OP_HELLO_OK,
                                OP_REQ, OP_RESP,
                                SUPPORTED_CAPS, SUPPORTED_VERSIONS,
                                FrameTooLargeError,
                                ProtocolVersionError, TornFrameError,
                                WireClient, encode_frame,
                                encode_frame_parts, pack_bin_payload,
                                read_frame, send_frame)

__all__ = ["TcpServe", "JsonlClient", "handle_request",
           "handle_request_segs", "MULTI_MAX_REQS"]

# One multi frame may carry at most this many sub-requests: bounds the
# per-frame work admission charges as one unit, and keeps the merged
# response under MAX_PAYLOAD for any sane row width.
MULTI_MAX_REQS = 4096


def _py(v):
    # Non-finite floats serialize as null: json.dumps would otherwise emit
    # Python-only NaN/Infinity tokens that strict parsers reject, and a
    # published snapshot CAN hold non-finite rows (observe-mode guards
    # count them without reverting).
    if isinstance(v, np.ndarray):
        out = v.tolist()
        if v.dtype.kind == "f" and not np.isfinite(v).all():
            out = scrub_nonfinite(out)
        return out
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return None if not math.isfinite(v) else float(v)
    return v


def _seg_ref(segs: list, arr) -> dict:
    """Park one result array in the segment list, return its payload
    placeholder. Gather outputs are C-contiguous by construction; the
    defensive copy below fires only for exotic strides."""
    arr = np.asarray(arr)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    segs.append(arr)
    return {"__seg__": len(segs) - 1}


def handle_request_segs(server: ReadServer, req) -> tuple[dict, list]:
    """One request → ``(response, segments)``: the response dict holds
    ``{"__seg__": i}`` placeholders where result ARRAYS go, and
    ``segments`` the arrays themselves — the transport decides whether
    to JSON-materialize them (:func:`handle_request`) or write their
    bytes straight into a FLAG_BIN frame (zero-copy sessions)."""
    segs: list = []
    if not isinstance(req, dict):
        # Valid JSON but not an object ('[1]', 'null'): still one error
        # response, never a dropped connection.
        return ({"ok": False,
                 "error": f"request must be a JSON object, got "
                          f"{type(req).__name__}"}, segs)
    try:
        op = req.get("op")
        if op == "pull":
            step, vals = server.pull(req["table"], req["ids"])
            return ({"ok": True, "step": step,
                     "values": _seg_ref(segs, vals)}, segs)
        if op == "score":
            step, scores = server.score_linear(
                req["feat_ids"], req["feat_vals"],
                table=req.get("table", "weights"),
                link=req.get("link", "sigmoid"))
            return ({"ok": True, "step": step,
                     "scores": _seg_ref(segs, scores)}, segs)
        if op == "topk":
            step, items, scores = server.topk(
                req["users"], int(req.get("k", 10)),
                item_table=req.get("item_table", "item_factors"),
                user_leaf=int(req.get("user_leaf", 0)))
            return ({"ok": True, "step": step,
                     "items": _seg_ref(segs, items),
                     "scores": _seg_ref(segs, scores)}, segs)
        if op == "multi":
            return _handle_multi(server, req, segs), segs
        if op == "stats":
            return {"ok": True, **server.stats()}, segs
        return {"ok": False, "error": f"unknown op {op!r}"}, segs
    except NoSnapshotError as e:
        return {"ok": False, "error": str(e), "retryable": True}, segs
    except (KeyError, IndexError, TypeError, ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}, segs


def _handle_multi(server: ReadServer, req: dict, segs: list) -> dict:
    """The batched multi-lookup op: every sub-request in ``reqs``
    executes as ONE :meth:`ReadServer.multi` batch (one snapshot
    binding, one merged gather per table). Sub-request failures ride
    inside their own result entry — siblings are unaffected."""
    reqs = req.get("reqs")
    if not isinstance(reqs, list):
        return {"ok": False, "error": "multi needs a 'reqs' list"}
    if len(reqs) > MULTI_MAX_REQS:
        return {"ok": False,
                "error": f"multi carries {len(reqs)} requests, "
                         f"cap {MULTI_MAX_REQS}"}
    calls = []
    for r in reqs:
        if isinstance(r, dict):
            calls.append((r.get("op"), r))
        else:
            calls.append(("__not_a_dict__", {}))
    results = server.multi(calls)  # NoSnapshotError propagates whole
    out = []
    for (kind, _payload), r, sub in zip(calls, results, reqs):
        if kind == "__not_a_dict__":
            out.append({"ok": False,
                        "error": f"request must be a JSON object, got "
                                 f"{type(sub).__name__}"})
        elif isinstance(r, NoSnapshotError):
            out.append({"ok": False, "error": str(r), "retryable": True})
        elif isinstance(r, BaseException):
            out.append({"ok": False,
                        "error": f"{type(r).__name__}: {r}"})
        elif kind == "pull":
            step, vals = r
            out.append({"ok": True, "step": step,
                        "values": _seg_ref(segs, vals)})
        elif kind == "score":
            step, scores = r
            out.append({"ok": True, "step": step,
                        "scores": _seg_ref(segs, scores)})
        elif kind == "stats":
            out.append({"ok": True, **r})
        else:  # topk
            step, items, scores = r
            out.append({"ok": True, "step": step,
                        "items": _seg_ref(segs, items),
                        "scores": _seg_ref(segs, scores)})
    return {"ok": True, "results": out}


def _jsonify_resp(node, segs):
    """Resolve segment placeholders into JSON-safe lists (:func:`_py`)
    — the compat path for sessions that did not negotiate CAP_BIN."""
    if isinstance(node, dict):
        if set(node) == {"__seg__"}:
            return _py(segs[node["__seg__"]])
        return {k: _jsonify_resp(v, segs) for k, v in node.items()}
    if isinstance(node, list):
        return [_jsonify_resp(v, segs) for v in node]
    return node


def handle_request(server: ReadServer, req: dict) -> dict:
    """One request → one JSON-safe response dict (transport-independent:
    the TCP handler's non-binary sessions and the in-process client in
    tests both ride this)."""
    resp, segs = handle_request_segs(server, req)
    return _jsonify_resp(resp, segs) if segs else resp


def _safe_dumps(resp: dict) -> bytes:
    try:
        return json.dumps(resp, allow_nan=False).encode("utf-8")
    except ValueError:
        # Belt-and-braces: _py() nulls non-finite floats, so any stray
        # NaN here is a protocol bug — fail the one response, not the
        # wire contract.
        return json.dumps(
            {"ok": False,
             "error": "non-finite value in response"}).encode("utf-8")


class TcpServe:
    """Serve a :class:`ReadServer` on ``127.0.0.1:port`` (0 = ephemeral;
    read the bound port from :attr:`port`). ``start()`` returns
    immediately (daemon threads); ``close()`` shuts the socket down.

    ``max_inflight`` seeds the default admission budget (cost units of
    concurrently-EXECUTING work across all connections; excess is shed
    with BUSY) — pass ``admission=`` for per-op cost weights and a
    latency-target governor (:mod:`fps_tpu.serve.admission`);
    ``caps=`` limits which wire capabilities this server will grant
    (``multi``/``bin``/``crc_light``, default: all).
    ``conn_timeout_s`` reaps connections whose peer goes silent
    mid-conversation; the (session, req_id) → response replay LRU that
    makes client resends idempotent is bounded BOTH by entries
    (``replay_cache``) and by payload bytes (``replay_cache_bytes`` —
    the binding bound in practice: responses vary from tens of bytes to
    MiBs, and fairness between peers is a byte property). Wire-plane
    metrics ride the ReadServer's recorder; :meth:`wire_stats` exposes
    the same counts as plain ints for tests and scenarios."""

    def __init__(self, server: ReadServer, *, host: str = "127.0.0.1",
                 port: int = 0, max_inflight: int = 64,
                 conn_timeout_s: float = 60.0,
                 replay_cache: int = 1024,
                 replay_cache_bytes: int = 8 << 20,
                 admission: AdmissionController | None = None,
                 caps=SUPPORTED_CAPS):
        read_server = server
        tcp_serve = self
        self._read_server = server
        # Admission: cost-weighted and (optionally) latency-governed
        # (fps_tpu/serve/admission.py). The default reproduces the old
        # semaphore semantics — unit-ish costs against max_inflight.
        self.admission = (AdmissionController(max_cost=float(max_inflight))
                          if admission is None else admission)
        self._caps = frozenset(caps)
        self._stats_lock = threading.Lock()
        self._replay: collections.OrderedDict = collections.OrderedDict()
        self._replay_cap = int(replay_cache)
        self._replay_max_bytes = int(replay_cache_bytes)
        self._replay_bytes = 0
        self._counts = {"torn_frames": 0, "shed_requests": 0,
                        "deadline_exceeded": 0, "dedup_replays": 0,
                        "framed_conns": 0, "replay_evictions": 0,
                        "dropped_accepts": 0, "bin_responses": 0,
                        "crc_light_frames": 0, "multi_frames": 0}

        class Handler(socketserver.StreamRequestHandler):
            timeout = conn_timeout_s

            def handle(self):
                # Request/response RPC: Nagle only adds delayed-ACK
                # stalls on single-write responses.
                self.connection.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    directive = net_fault_check("accept", "serve")
                except OSError:
                    return  # injected accept failure: connection dies
                if directive == "drop":
                    tcp_serve._bump("dropped_accepts")
                    return  # one-way partition: accepted, never served
                # Framed wire only (the PR-16 dual-stack peek is
                # retired): a legacy line-JSON peer fails the first
                # frame's magic gate inside the handshake and gets a
                # counted OP_ERR + dropped connection.
                tcp_serve._bump("framed_conns")
                self._handle_framed()

            # -- framed path --------------------------------------------

            def _send(self, op, req_id, payload: bytes):
                send_frame(self.connection,
                           encode_frame(op, req_id, payload), "serve")

            def _handle_framed(self):
                recorder = read_server.recorder
                try:
                    if not self._handshake():
                        return
                    while True:
                        try:
                            fr = read_frame(self.rfile)
                        except (TornFrameError, FrameTooLargeError,
                                ProtocolVersionError) as e:
                            tcp_serve._bump("torn_frames")
                            _emit_metric(recorder, "inc",
                                         "net.torn_frames", 1)
                            _emit_event(recorder, "wire_torn_frame",
                                        reason=str(e))
                            try:
                                self._send(OP_ERR, 0, _safe_dumps(
                                    {"ok": False, "error": str(e)}))
                            except OSError:
                                pass
                            return  # drop the connection loudly
                        if fr is None:
                            return  # clean EOF at a frame boundary
                        if fr.op != OP_REQ:
                            self._send(OP_ERR, fr.req_id, _safe_dumps(
                                {"ok": False,
                                 "error": f"unexpected op {fr.op}"}))
                            return
                        self._serve_one(fr, recorder)
                except (TimeoutError, ConnectionError, OSError):
                    return  # peer vanished / partitioned: reap quietly

            def _handshake(self) -> bool:
                try:
                    fr = read_frame(self.rfile)
                except (TornFrameError, FrameTooLargeError,
                        ProtocolVersionError) as e:
                    tcp_serve._bump("torn_frames")
                    _emit_metric(read_server.recorder, "inc",
                                 "net.torn_frames", 1)
                    try:
                        self._send(OP_ERR, 0, _safe_dumps(
                            {"ok": False, "error": str(e)}))
                    except OSError:
                        pass
                    return False
                if fr is None or fr.op != OP_HELLO:
                    self._send(OP_ERR, 0, _safe_dumps(
                        {"ok": False,
                         "error": "expected HELLO as the first frame"}))
                    return False
                hello = fr.json()
                offered = {int(v) for v in hello.get("versions", ())}
                common = offered & set(SUPPORTED_VERSIONS)
                if not common:
                    self._send(OP_ERR, 0, _safe_dumps(
                        {"ok": False,
                         "error": "no common protocol version",
                         "supported": list(SUPPORTED_VERSIONS)}))
                    return False
                self.wire_session = str(
                    hello.get("session", f"conn-{id(self)}"))
                self.wire_version = max(common)
                # Capability negotiation (additive — the protocol
                # version does not move): grant the intersection of
                # what the client offered and what this server allows.
                # Old clients offer nothing and get nothing; every
                # pre-capability frame shape still works.
                offered_caps = {str(c) for c in hello.get("caps", ())}
                self.wire_caps = offered_caps & tcp_serve._caps
                self._send(OP_HELLO_OK, 0, _safe_dumps(
                    {"ok": True, "version": self.wire_version,
                     "caps": sorted(self.wire_caps)}))
                return True

            def _serve_one(self, fr, recorder):
                envelope = fr.json()
                key = (self.wire_session, fr.req_id)
                cached = tcp_serve._replay_get(key)
                if cached is not None:
                    # Idempotent resend after a reconnect: replay the
                    # recorded response, never execute twice.
                    send_frame(self.connection, cached, "serve")
                    return
                deadline = envelope.get("d")
                if deadline is not None and float(deadline) <= 0:
                    tcp_serve._bump("deadline_exceeded")
                    _emit_metric(recorder, "inc",
                                 "net.deadline_exceeded", 1)
                    self._send(OP_RESP, fr.req_id, _safe_dumps(
                        {"ok": False, "error": "deadline exceeded",
                         "retryable": True, "deadline_exceeded": True}))
                    return
                q = envelope.get("q")
                if (isinstance(q, dict) and q.get("op") == "multi"
                        and CAP_MULTI not in self.wire_caps):
                    # A multi frame on a session that never negotiated
                    # the capability is a protocol bug, not load.
                    self._send(OP_RESP, fr.req_id, _safe_dumps(
                        {"ok": False,
                         "error": "multi not negotiated on this "
                                  "session"}))
                    return
                cost = tcp_serve.admission.cost_of(q)
                if not tcp_serve.admission.try_admit(cost):
                    # Admission control: the cost budget (queue depth
                    # in op-weighted units, latency-governed) is spent.
                    # Shed with a retryable BUSY — bounded latency
                    # beats an unbounded queue (docs/STALENESS.md).
                    tcp_serve._bump("shed_requests")
                    _emit_metric(recorder, "inc",
                                 "net.shed_requests", 1)
                    self._send(OP_BUSY, fr.req_id, _safe_dumps(
                        {"ok": False, "error": "server busy",
                         "retryable": True, "busy": True}))
                    return
                t0 = time.monotonic()
                try:
                    resp, segs = handle_request_segs(read_server, q)
                finally:
                    tcp_serve.admission.release(
                        cost, time.monotonic() - t0)
                if isinstance(q, dict) and q.get("op") == "multi":
                    tcp_serve._bump("multi_frames")
                data = self._encode_resp(fr.req_id, resp, segs,
                                         recorder)
                if resp.get("ok"):
                    # Only EXECUTED successes are replayable; errors
                    # and sheds must re-execute on resend.
                    tcp_serve._replay_put(key, data)
                send_frame(self.connection, data, "serve")

            def _encode_resp(self, req_id, resp, segs, recorder):
                """Encode a response for THIS session's capabilities.

                * ``bin`` negotiated and array segments present →
                  binary payload framing: the raw table rows ride as
                  memoryview segments straight off the snapshot (no
                  base64, no JSON digit-printing, no copy).
                * ``crc_light`` negotiated and the payload is large →
                  header-only CRC trailer (the loopback-trusted mode;
                  default sessions keep the full-payload CRC).

                Returns either ``bytes`` or a parts list; both
                ``send_frame`` and the replay cache accept either.
                """
                use_bin = bool(segs) and CAP_BIN in self.wire_caps
                if use_bin:
                    parts = pack_bin_payload(resp, segs)
                    flags = FLAG_BIN
                else:
                    # No bin capability: materialize segments into the
                    # JSON body (the compatible, copying path).
                    parts = [_safe_dumps(_jsonify_resp(resp, segs))]
                    flags = 0
                nbytes = sum(
                    getattr(p, "nbytes", None) or len(p) for p in parts)
                crc_light = (CAP_CRC_LIGHT in self.wire_caps
                             and nbytes > CRC_LIGHT_THRESHOLD)
                if use_bin:
                    tcp_serve._bump("bin_responses")
                    _emit_metric(recorder, "inc",
                                 "net.bin_responses", 1)
                if crc_light:
                    tcp_serve._bump("crc_light_frames")
                    _emit_metric(recorder, "inc",
                                 "net.crc_light_frames", 1)
                if not use_bin and not crc_light:
                    return encode_frame(OP_RESP, req_id, parts[0])
                return encode_frame_parts(
                    OP_RESP, req_id, parts,
                    flags=flags, crc_light=crc_light)

        self._tcp = socketserver.ThreadingTCPServer(
            (host, port), Handler, bind_and_activate=True)
        self._tcp.daemon_threads = True
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="fps-serve-tcp",
            daemon=True)
        self.host, self.port = self._tcp.server_address[:2]

    # -- shared wire state (handler threads) --------------------------------

    def _bump(self, name: str) -> None:
        with self._stats_lock:
            self._counts[name] += 1

    def _replay_get(self, key):
        with self._stats_lock:
            data = self._replay.get(key)
            if data is not None:
                self._replay.move_to_end(key)
                self._counts["dedup_replays"] += 1
            return data

    @staticmethod
    def _frame_nbytes(data) -> int:
        """Wire size of a cached response — bytes or a parts list
        (binary responses are cached as the scatter-gather parts they
        were sent as; no join on the hot path)."""
        if isinstance(data, (bytes, bytearray, memoryview)):
            return len(data)
        return sum(getattr(p, "nbytes", None) or len(p) for p in data)

    def _replay_put(self, key, data) -> None:
        recorder = self._read_server.recorder
        with self._stats_lock:
            old = self._replay.pop(key, None)
            if old is not None:
                self._replay_bytes -= self._frame_nbytes(old)
            self._replay[key] = data
            self._replay_bytes += self._frame_nbytes(data)
            # Byte bound first (the binding one — fairness between a
            # MiB-response tenant and a tens-of-bytes tenant is a byte
            # property), entry bound as a backstop. Strict LRU order:
            # oldest-touched entries go first, pinned by the test.
            # The just-inserted entry is IN FLIGHT (its response may
            # still be resent after a reconnect) — eviction never
            # touches it, even when it alone exceeds the byte bound.
            evicted = 0
            while (len(self._replay) > 1
                   and (self._replay_bytes > self._replay_max_bytes
                        or len(self._replay) > self._replay_cap)):
                _k, v = self._replay.popitem(last=False)
                self._replay_bytes -= self._frame_nbytes(v)
                evicted += 1
            self._counts["replay_evictions"] += evicted
        if evicted:
            # Outside the stats lock: the recorder takes its own.
            _emit_metric(recorder, "inc",
                         "net.replay_cache_evictions", evicted)

    def replay_bytes(self) -> int:
        """Current replay-cache payload bytes (<= replay_cache_bytes)."""
        with self._stats_lock:
            return self._replay_bytes

    def wire_stats(self) -> dict:
        """Plain-int wire counters (scenario/bench evidence):
        torn_frames, shed_requests, deadline_exceeded, dedup_replays,
        framed_conns, replay_evictions, dropped_accepts,
        bin_responses, crc_light_frames, multi_frames — plus the
        admission controller's snapshot under ``"admission"``."""
        with self._stats_lock:
            out = dict(self._counts)
        out["admission"] = self.admission.stats()
        return out

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "TcpServe":
        self._thread.start()
        return self

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


class JsonlClient:
    """DEPRECATED compat shim: the old line-protocol client surface
    (constructor, ``request()``, ``close()``, context manager) speaking
    the FRAMED wire through :class:`~fps_tpu.serve.wire.WireClient`.
    Existing tools/tests keep working and silently gain deadlines,
    bounded retry, and idempotent reconnect. The dual-stack server that
    accepted raw line-JSON peers is retired (``docs/serving.md``); new
    code should use ``WireClient``."""

    def __init__(self, host: str, port: int, *, timeout: float = 10.0):
        self._wire = WireClient(host, port, timeout=timeout,
                                deadline_s=timeout)

    def request(self, req: dict) -> dict:
        return self._wire.request(req)

    def close(self) -> None:
        self._wire.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
