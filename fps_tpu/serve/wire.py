"""Versioned, length-prefixed binary framing for the serve wire.

PR 15 gave the filesystem planes a fault model; this module is the same
discipline for the WIRE plane. The line-JSON transport in
:mod:`fps_tpu.serve.net` had no integrity or liveness story: a peer
dying mid-write hands the reader half a JSON line, a slow peer holds a
blocking ``readline`` hostage forever, and a reconnecting client cannot
tell whether its in-flight request executed. Framing fixes all three:

``frame := header(20B) || payload || crc32(header || payload)(4B)``
``header := magic(4s) | version(u16) | op(u8) | flags(u8) |``
``          req_id(u64) | payload_len(u32)``   (network byte order)

* **magic** — ``\\xabFPS``; the first byte is deliberately outside
  ASCII, so a stray legacy line-JSON peer (whose bytes always start
  ``{`` or whitespace) fails the magic gate on its FIRST frame and is
  rejected loudly — the PR-16 dual-stack peek that once routed such
  peers to a compat loop is retired (``docs/serving.md``).
* **version** — negotiated by a HELLO exchange: the client offers its
  versions, the server picks the highest common one or rejects LOUDLY
  (:class:`ProtocolVersionError`), never guesses.
* **req_id** — client-assigned, monotone per session, REUSED across
  retries of the same logical request: the server's replay cache keyed
  on ``(session, req_id)`` makes reconnect-resend idempotent (a retry
  of an already-executed request replays the cached response instead
  of executing twice).
* **crc32 + length** — a torn frame (peer died mid-write, injected
  ``cut`` fault) is detected by short read or checksum mismatch and
  rejected as :class:`TornFrameError` with the failing layer named;
  it is NEVER decoded and never poisons the stream. Oversized length
  prefixes (corruption or abuse) reject as
  :class:`FrameTooLargeError` before any allocation.

:class:`WireClient` is the failure-aware client: per-request deadline
budgets, bounded retry with the PR-15 sha256-jittered backoff
(:func:`fps_tpu.core.retry.classify_net` decides transient vs fatal),
reconnect-with-backoff that re-handshakes under the SAME session id
and resends under the SAME req_id (the dedupe key), and honest
accounting (``net.retries`` / ``net.reconnects`` /
``net.deadline_exceeded`` through the obs registry when a recorder is
wired, plus plain attributes for tests).

Payloads are JSON (the request/response dicts of
:func:`fps_tpu.serve.net.handle_request`, unchanged) — the framing adds
integrity and liveness, not a new schema language.

Stdlib-only by contract: the jax-free serving CLI (``tools/serve.py``)
and any login-node client import this module without jax or numpy.
"""

from __future__ import annotations

import binascii
import io
import json
import os
import socket
import struct
import threading
import time
import zlib

from fps_tpu.core.retry import (DEFAULT_NET_RETRY, classify_net,
                                net_fault_check)

__all__ = [
    "PROTO_VERSION", "MAGIC", "MAX_PAYLOAD",
    "OP_HELLO", "OP_HELLO_OK", "OP_REQ", "OP_RESP", "OP_BUSY", "OP_ERR",
    "CAP_MULTI", "CAP_BIN", "CAP_CRC_LIGHT", "SUPPORTED_CAPS",
    "DEFAULT_CLIENT_CAPS", "FLAG_BIN", "FLAG_CRC_LIGHT",
    "CRC_LIGHT_THRESHOLD",
    "Frame", "WireError", "TornFrameError", "FrameTooLargeError",
    "ProtocolVersionError", "ServerBusyError",
    "encode_frame", "encode_frame_parts", "decode_frame", "read_frame",
    "pack_bin_payload", "split_bin_payload", "decode_bin_response",
    "WireClient",
]

MAGIC = b"\xabFPS"
PROTO_VERSION = 1
SUPPORTED_VERSIONS = (1,)
# Length-prefix cap: the largest legitimate payload (a dense topk over
# a big batch) is well under a MiB; 16 MiB rejects corrupt/hostile
# prefixes before any allocation.
MAX_PAYLOAD = 16 << 20

# HELLO-negotiated CAPABILITIES (the version stays 1: capabilities are
# strictly additive, and a peer that never offers them gets the exact
# PR-16 wire — old clients keep working against new servers and vice
# versa). The server replies with the intersection of what the client
# offered and what it supports; a capability is live on a session only
# when BOTH sides named it.
CAP_MULTI = "multi"          # batched multi-lookup op in one frame
CAP_BIN = "bin"              # binary row segments in responses (FLAG_BIN)
CAP_CRC_LIGHT = "crc_light"  # header-only CRC above CRC_LIGHT_THRESHOLD
SUPPORTED_CAPS = (CAP_MULTI, CAP_BIN, CAP_CRC_LIGHT)
# Clients offer only CAP_MULTI by default: binary responses change the
# response value types (ndarrays, NaN passthrough) and crc-light trades
# payload integrity for throughput — both are explicit opt-ins
# (loopback-trusted, throughput-hungry sessions like bench serve_scale).
DEFAULT_CLIENT_CAPS = (CAP_MULTI,)

# Frame flag bits (header ``flags`` byte).
FLAG_BIN = 0x01        # payload = u32 meta_len | meta json | raw segments
FLAG_CRC_LIGHT = 0x02  # CRC trailer covers the HEADER only (negotiated)

# Payloads at or below this size always carry the full CRC even on a
# crc-light session: integrity of small control/response frames is
# ~free, and the ~2% CRC tax only matters on MiB-scale batched rows.
CRC_LIGHT_THRESHOLD = 64 << 10

_HEADER = struct.Struct("!4sHBBQI")  # magic, version, op, flags, id, len
_CRC = struct.Struct("!I")

OP_HELLO = 1      # client -> server: version offer + session id
OP_HELLO_OK = 2   # server -> client: chosen version
OP_REQ = 3        # client -> server: one request envelope
OP_RESP = 4       # server -> client: one response
OP_BUSY = 5       # server -> client: load-shed, retry after backoff
OP_ERR = 6        # server -> client: protocol-level rejection


class WireError(Exception):
    """Base for protocol-layer failures."""


class TornFrameError(WireError, ConnectionError):
    """A frame that stopped mid-air or failed its checksum — short
    header, short payload, short CRC trailer, bad magic, or CRC
    mismatch. Subclasses ConnectionError deliberately: a torn frame
    means the CONNECTION is garbage (reconnect-and-resend is the
    correct response, and :func:`classify_net` already says so); the
    frame itself is never decoded."""


class FrameTooLargeError(WireError):
    """Length prefix beyond :data:`MAX_PAYLOAD` — corruption or abuse;
    fatal, never retried."""


class ProtocolVersionError(WireError):
    """No common protocol version (or a frame in an unknown version) —
    fatal: retrying cannot negotiate a version we do not speak."""


class ServerBusyError(WireError):
    """The server shed this request under admission control (OP_BUSY).
    Retryable WITHOUT reconnecting — the connection is healthy, the
    server is just full; :class:`WireClient` backs off and resends,
    surfacing this only when the deadline budget exhausts."""


class Frame:
    """One decoded frame. Plain attribute record (no numpy, no
    dataclass machinery — this sits on the per-request hot path)."""

    __slots__ = ("op", "req_id", "payload", "version", "flags")

    def __init__(self, op, req_id, payload, version=PROTO_VERSION,
                 flags=0):
        self.op = op
        self.req_id = req_id
        self.payload = payload
        self.version = version
        self.flags = flags

    def json(self) -> dict:
        return json.loads(self.payload)


def _dumps(obj) -> bytes:
    return json.dumps(obj, allow_nan=False).encode("utf-8")


def encode_frame(op: int, req_id: int, payload: bytes, *,
                 version: int = PROTO_VERSION, flags: int = 0) -> bytes:
    """Serialize one frame: header + payload + CRC32 trailer."""
    if len(payload) > MAX_PAYLOAD:
        raise FrameTooLargeError(
            f"payload {len(payload)} bytes exceeds cap {MAX_PAYLOAD}")
    head = _HEADER.pack(MAGIC, version, op, flags, req_id, len(payload))
    # Incremental CRC + single join: no full-payload concat copies on
    # the per-request hot path.
    crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    return b"".join((head, payload, _CRC.pack(crc)))


def _as_buf(part):
    """Normalize any C-contiguous buffer (bytes, memoryview, ndarray)
    to a flat byte view WITHOUT copying the underlying memory."""
    if isinstance(part, (bytes, bytearray)):
        return part
    mv = part if isinstance(part, memoryview) else memoryview(part)
    if mv.ndim != 1 or mv.format != "B":
        mv = mv.cast("B")
    return mv


def encode_frame_parts(op: int, req_id: int, parts, *,
                       version: int = PROTO_VERSION, flags: int = 0,
                       crc_light: bool = False) -> list:
    """Scatter-gather frame encoder: header + the caller's buffers +
    CRC trailer, returned as a LIST of buffers for ``socket.sendmsg``
    — row bytes gathered off the mmap'd tables go straight to the
    kernel, never joined into an intermediate payload copy (the
    zero-copy response path; :func:`send_frame` accepts the list).

    ``crc_light=True`` (only on sessions that negotiated
    :data:`CAP_CRC_LIGHT`, for payloads above
    :data:`CRC_LIGHT_THRESHOLD`) computes the trailer over the header
    alone and sets :data:`FLAG_CRC_LIGHT` — the length prefix and
    header stay guarded, the MiB-scale row bytes skip the CRC pass."""
    bufs = [_as_buf(p) for p in parts]
    total = sum(len(b) for b in bufs)
    if total > MAX_PAYLOAD:
        raise FrameTooLargeError(
            f"payload {total} bytes exceeds cap {MAX_PAYLOAD}")
    if crc_light:
        flags |= FLAG_CRC_LIGHT
    head = _HEADER.pack(MAGIC, version, op, flags, req_id, total)
    crc = zlib.crc32(head)
    if not (flags & FLAG_CRC_LIGHT):
        for b in bufs:
            crc = zlib.crc32(b, crc)
    return [head, *bufs, _CRC.pack(crc & 0xFFFFFFFF)]


def _read_exact(rfile, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes or reject the frame as torn, naming the
    layer that came up short (the truncation tests assert the reason)."""
    buf = rfile.read(n)
    if buf is None:
        buf = b""
    while len(buf) < n:
        more = rfile.read(n - len(buf))
        if not more:
            raise TornFrameError(
                f"torn frame: {what} truncated "
                f"({len(buf)}/{n} bytes)")
        buf += more
    return buf


def read_frame(rfile, *, allowed_versions=SUPPORTED_VERSIONS,
               allow_crc_light: bool = False):
    """Read one complete frame from a buffered binary stream.

    Returns None on clean EOF AT a frame boundary (zero bytes read);
    any partial frame raises :class:`TornFrameError` with the
    truncated layer named, an unknown version raises
    :class:`ProtocolVersionError`, an oversized length prefix raises
    :class:`FrameTooLargeError` — all BEFORE any payload is decoded.

    ``allow_crc_light`` accepts frames whose trailer CRCs the header
    only (:data:`FLAG_CRC_LIGHT`) — legal ONLY on sessions that
    negotiated :data:`CAP_CRC_LIGHT`; an unnegotiated crc-light frame
    is rejected as torn (a peer must not be able to opt itself out of
    integrity unilaterally)."""
    # Magic is validated from the first 4 bytes ALONE, before waiting
    # for the rest of the header: a non-wire peer (e.g. a retired
    # legacy line-JSON client) may send fewer bytes than a full header
    # and then wait for a reply — it must fail fast with a torn-frame
    # OP_ERR, not hang until the connection timeout reaps it.
    first = rfile.read(len(MAGIC))
    if not first:
        return None
    if len(first) < len(MAGIC):
        try:
            first += _read_exact(rfile, len(MAGIC) - len(first), "magic")
        except TornFrameError:
            raise TornFrameError(
                f"torn frame: header truncated "
                f"({len(first)}/{_HEADER.size} bytes)") from None
    if first != MAGIC:
        raise TornFrameError(
            f"torn frame: bad magic {first!r} (mid-stream desync or a "
            f"non-wire peer)")
    try:
        first += _read_exact(rfile, _HEADER.size - len(MAGIC), "header")
    except TornFrameError:
        raise TornFrameError(
            f"torn frame: header truncated "
            f"({len(first)}/{_HEADER.size} bytes)") from None
    _magic, version, op, flags, req_id, length = _HEADER.unpack(first)
    if version not in allowed_versions:
        raise ProtocolVersionError(
            f"unsupported protocol version {version} "
            f"(supported: {list(allowed_versions)})")
    if length > MAX_PAYLOAD:
        raise FrameTooLargeError(
            f"frame announces {length} payload bytes, cap {MAX_PAYLOAD}")
    payload = _read_exact(rfile, length, "payload") if length else b""
    (crc,) = _CRC.unpack(_read_exact(rfile, _CRC.size, "crc trailer"))
    if flags & FLAG_CRC_LIGHT:
        if not allow_crc_light:
            raise TornFrameError(
                "torn frame: crc-light flag on a session that did not "
                "negotiate it")
        want = zlib.crc32(first) & 0xFFFFFFFF
    else:
        want = zlib.crc32(payload, zlib.crc32(first)) & 0xFFFFFFFF
    if crc != want:
        raise TornFrameError(
            f"torn frame: crc mismatch (got {crc:#010x}, "
            f"want {want:#010x})")
    return Frame(op, req_id, payload, version, flags)


def decode_frame(data: bytes, *, allow_crc_light: bool = False):
    """Decode one frame from a complete byte string (tests and tools).
    Truncated input rejects exactly like a torn stream read."""
    fr = read_frame(io.BytesIO(data), allow_crc_light=allow_crc_light)
    if fr is None:
        raise TornFrameError("torn frame: empty input")
    return fr


# ---------------------------------------------------------------------------
# Binary (zero-copy) response payloads — FLAG_BIN.
#
# ``payload := meta_len(u32) || meta_json || seg_0 || seg_1 || ...``
# where ``meta = {"resp": <response dict with {"__seg__": i}
# placeholders>, "segs": [{"dtype", "shape", "nbytes"}, ...]}``. The
# server packs each segment as a memoryview over the fancy-index gather
# output (O(batch) rows, already a fresh buffer — the mmap'd table
# itself is never materialized); the client reconstructs ndarrays with
# ``np.frombuffer`` over payload slices. numpy stays a LAZY import on
# the client side: the stdlib-only import contract holds, and only
# sessions that negotiated CAP_BIN ever decode these.
# ---------------------------------------------------------------------------

_U32 = struct.Struct("!I")


def pack_bin_payload(resp: dict, segs) -> list:
    """Build the parts list for a FLAG_BIN payload: ``resp`` is the
    response dict with ``{"__seg__": i}`` placeholders, ``segs`` the
    matching buffers (ndarrays/memoryviews, C-contiguous). Returns
    buffers ready for :func:`encode_frame_parts` — segment bytes are
    referenced, not copied."""
    bufs = [_as_buf(s) for s in segs]
    descs = []
    for s, b in zip(segs, bufs):
        dt = getattr(s, "dtype", None)
        descs.append({
            "dtype": "B" if dt is None else str(getattr(dt, "str", dt)),
            "shape": list(getattr(s, "shape", (len(b),))),
            "nbytes": len(b)})
    meta = {"resp": resp, "segs": descs}
    mb = _dumps(meta)
    return [_U32.pack(len(mb)), mb, *bufs]


def split_bin_payload(payload) -> tuple[dict, list]:
    """Inverse of :func:`pack_bin_payload` framing: returns
    ``(meta, [seg memoryviews])`` — slices of the received payload,
    no copies."""
    mv = memoryview(payload)
    if len(mv) < _U32.size:
        raise TornFrameError("torn frame: bin payload shorter than its "
                             "meta length prefix")
    (mlen,) = _U32.unpack(mv[:_U32.size])
    end = _U32.size + mlen
    if end > len(mv):
        raise TornFrameError("torn frame: bin meta block truncated")
    meta = json.loads(bytes(mv[_U32.size:end]))
    segs, off = [], end
    for d in meta.get("segs", ()):
        n = int(d["nbytes"])
        if off + n > len(mv):
            raise TornFrameError("torn frame: bin segment truncated")
        segs.append(mv[off:off + n])
        off += n
    return meta, segs


def _resolve_segs(node, arrays):
    if isinstance(node, dict):
        if set(node) == {"__seg__"}:
            return arrays[int(node["__seg__"])]
        return {k: _resolve_segs(v, arrays) for k, v in node.items()}
    if isinstance(node, list):
        return [_resolve_segs(v, arrays) for v in node]
    return node


def decode_bin_response(payload) -> dict:
    """Decode a FLAG_BIN payload into the response dict, segment
    placeholders resolved to ndarrays (``np.frombuffer`` over payload
    slices — the copy happens only if the caller writes)."""
    import numpy as np  # lazy: only CAP_BIN sessions pay the import

    meta, segs = split_bin_payload(payload)
    arrays = [np.frombuffer(seg, dtype=np.dtype(d["dtype"])).reshape(
        d["shape"]) for seg, d in zip(segs, meta.get("segs", ()))]
    return _resolve_segs(meta["resp"], arrays)


# ---------------------------------------------------------------------------
# Seam-aware socket I/O (shared by client and server).
# ---------------------------------------------------------------------------

def _sendall_parts(sock, parts) -> None:
    """Scatter-gather sendall: one ``sendmsg`` (kernel writev) per
    <=512-buffer slice with partial-send continuation — the frame's
    header, row segments, and CRC trailer leave the process without
    ever being joined into one contiguous copy."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b)
            for b in parts]
    i = 0
    while i < len(bufs):
        sent = sock.sendmsg(bufs[i:i + 512])  # IOV_MAX headroom
        while i < len(bufs) and sent >= len(bufs[i]):
            sent -= len(bufs[i])
            i += 1
        if sent:
            bufs[i] = bufs[i][sent:]


def send_frame(sock, data, peer_class: str,
               sleep=time.sleep) -> None:
    """Send one encoded frame through the :func:`net_fault_check` seam.
    ``data`` is either one contiguous frame (:func:`encode_frame`) or a
    parts LIST (:func:`encode_frame_parts` — scatter-gather, zero-copy).
    Honors the injector's directives: ``("cut", n)`` transmits only the
    first ``n`` bytes and kills the connection (the torn-frame
    producer); ``("trickle", chunk, delay_s)`` drips the frame out
    ``chunk`` bytes at a time (the slow peer)."""
    directive = net_fault_check("send", peer_class)
    if isinstance(data, (list, tuple)):
        if directive is None:
            _sendall_parts(sock, data)
            return
        # Fault path only (never the hot path): directives address byte
        # offsets, so flatten the parts to apply cut/trickle exactly.
        data = b"".join(bytes(p) if not isinstance(p, (bytes, bytearray))
                        else p for p in data)
    if directive is None:
        sock.sendall(data)
        return
    if isinstance(directive, tuple) and directive[0] == "cut":
        sock.sendall(data[:directive[1]])
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        raise ConnectionResetError(
            "faultnet cut the frame mid-send "
            f"({directive[1]}/{len(data)} bytes left the host)")
    if isinstance(directive, tuple) and directive[0] == "trickle":
        chunk, delay_s = int(directive[1]), float(directive[2])
        for i in range(0, len(data), chunk):
            sock.sendall(data[i:i + chunk])
            if delay_s > 0:
                sleep(delay_s)
        return
    sock.sendall(data)  # unknown directive: ignore, per seam contract


def recv_frame(rfile, peer_class: str, *,
               allowed_versions=SUPPORTED_VERSIONS,
               allow_crc_light: bool = False):
    """Read one frame through the seam (``recv`` faults: partition
    timeouts, delays) then :func:`read_frame`."""
    net_fault_check("recv", peer_class)
    return read_frame(rfile, allowed_versions=allowed_versions,
                      allow_crc_light=allow_crc_light)


def _emit_metric(recorder, kind: str, name: str, value,
                 **labels) -> None:
    # Same guarded shape as serve.watcher._emit_metric, duplicated so
    # this module keeps its zero-dependency import graph (no recorder =
    # no emission; the WireClient attributes still count).
    if recorder is None:
        return
    getattr(recorder, kind)(name, value, **labels)


# ---------------------------------------------------------------------------
# The failure-aware client.
# ---------------------------------------------------------------------------

class WireClient:
    """Blocking framed client with deadlines, bounded retry, and
    idempotent reconnect.

    Every ``request()`` gets ONE req_id for its whole retry journey:
    transient failures (refused/reset/timeout/torn frame — see
    :func:`classify_net`) drop the connection, back off on the policy's
    deterministic jittered schedule, re-handshake under the same
    session id, and RESEND under the same req_id, so the server's
    replay cache guarantees at-most-once execution. The per-request
    deadline budget caps the whole journey (attempts + backoffs +
    socket waits); when it exhausts, the last error surfaces.

    thread-safety: one in-flight request at a time (internal lock) —
    it is a blocking point-query client, like the line client it
    replaces; run one client per load thread for parallelism."""

    def __init__(self, host: str, port: int, *, timeout: float = 10.0,
                 deadline_s: float = 10.0, policy=None,
                 peer_class: str = "serve", session: str | None = None,
                 recorder=None, caps=DEFAULT_CLIENT_CAPS):
        self.host, self.port = host, int(port)
        self._timeout = float(timeout)
        self._deadline_s = float(deadline_s)
        self._policy = DEFAULT_NET_RETRY if policy is None else policy
        self._peer_class = peer_class
        self._recorder = recorder
        self.session = session or binascii.hexlify(
            os.urandom(8)).decode("ascii")
        self.version: int | None = None
        # Capabilities OFFERED in HELLO; ``self.caps`` holds what the
        # server granted (intersection) after the handshake. A server
        # predating capabilities replies without a "caps" key → empty
        # set → the exact PR-16 behavior.
        self._offered_caps = tuple(caps)
        self.caps: set = set()
        self._req_seq = 0
        self._sock = None
        self._rfile = None
        self._connected_once = False
        self._lock = threading.Lock()
        # Honest accounting, recorder or not.
        self.retries = 0
        self.reconnects = 0
        self.deadline_exceeded = 0
        self.busy_rejections = 0
        self._connect()

    # -- connection lifecycle ----------------------------------------------

    def _connect(self) -> None:
        net_fault_check("connect", self._peer_class)
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self._timeout)
        # Request/response RPC: Nagle only adds delayed-ACK stalls.
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        try:
            hello = {"versions": list(SUPPORTED_VERSIONS),
                     "session": self.session,
                     "caps": list(self._offered_caps)}
            send_frame(self._sock, encode_frame(OP_HELLO, 0,
                                                _dumps(hello)),
                       self._peer_class)
            fr = recv_frame(self._rfile, self._peer_class)
        except BaseException:
            self._drop()
            raise
        if fr is None:
            self._drop()
            raise ConnectionError("server closed during handshake")
        if fr.op == OP_ERR:
            err = fr.json().get("error", "handshake rejected")
            self._drop()
            raise ProtocolVersionError(err)
        if fr.op != OP_HELLO_OK:
            self._drop()
            raise TornFrameError(
                f"torn frame: expected HELLO_OK, got op {fr.op}")
        ok = fr.json()
        self.version = int(ok.get("version", PROTO_VERSION))
        self.caps = set(ok.get("caps", ())) & set(self._offered_caps)
        if self._connected_once:
            self.reconnects += 1
            _emit_metric(self._recorder, "inc", "net.reconnects", 1)
        self._connected_once = True

    def _drop(self) -> None:
        for closer in (self._rfile, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:
                    pass
        self._sock = self._rfile = None

    def close(self) -> None:
        self._drop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- requests -----------------------------------------------------------

    def request(self, req: dict, *, deadline_s: float | None = None,
                clock=time.monotonic, sleep=time.sleep) -> dict:
        """One request -> one response dict, surviving transient wire
        failures inside the deadline budget. Application-level errors
        (``ok: false`` responses from ``handle_request``) return to the
        caller unchanged — only TRANSPORT failures and server
        shed/deadline frames are retried here."""
        budget = (self._deadline_s if deadline_s is None
                  else float(deadline_s))
        with self._lock:
            self._req_seq += 1
            req_id = self._req_seq
            t0 = clock()
            attempt = 0
            last: BaseException | None = None
            while True:
                remaining = budget - (clock() - t0)
                if remaining <= 0:
                    break
                try:
                    return self._attempt(req, req_id, remaining)
                except (ProtocolVersionError, FrameTooLargeError):
                    raise  # speaking-past-each-other: never retry
                except ServerBusyError as e:
                    last = e
                    self.busy_rejections += 1
                    # Connection is healthy; do NOT reconnect.
                except (WireError, ConnectionError, TimeoutError,
                        OSError) as e:
                    if classify_net(e) != "retryable":
                        raise
                    last = e
                    self._drop()
                if attempt >= self._policy.retries:
                    break
                delay = self._policy.backoff_s(attempt)
                if clock() - t0 + delay > budget:
                    break
                self.retries += 1
                _emit_metric(self._recorder, "inc", "net.retries", 1,
                             peer_class=self._peer_class)
                sleep(delay)
                attempt += 1
            # Budget or retry budget exhausted.
            if isinstance(last, (TimeoutError, ServerBusyError)) or (
                    budget - (clock() - t0) <= 0):
                self.deadline_exceeded += 1
                _emit_metric(self._recorder, "inc",
                             "net.deadline_exceeded", 1)
            if last is None:
                last = TimeoutError(
                    f"request {req_id}: deadline budget {budget}s "
                    f"exhausted before the first attempt")
            raise last

    def _attempt(self, req: dict, req_id: int,
                 remaining: float) -> dict:
        if self._sock is None:
            self._connect()
        self._sock.settimeout(max(min(self._timeout, remaining), 1e-3))
        envelope = {"d": round(remaining, 3), "q": req}
        send_frame(self._sock, encode_frame(OP_REQ, req_id,
                                            _dumps(envelope)),
                   self._peer_class)
        while True:
            fr = recv_frame(self._rfile, self._peer_class,
                            allow_crc_light=CAP_CRC_LIGHT in self.caps)
            if fr is None:
                raise ConnectionError("server closed the connection")
            if fr.op == OP_BUSY:
                raise ServerBusyError(
                    "server shed the request under admission control")
            if fr.op == OP_ERR:
                raise TornFrameError(
                    f"torn frame: server protocol rejection: "
                    f"{fr.json().get('error')}")
            if fr.op != OP_RESP:
                raise TornFrameError(
                    f"torn frame: unexpected op {fr.op} mid-request")
            if fr.req_id != req_id:
                # A reply to an EARLIER attempt of this session that
                # the server flushed late; ours is still coming on
                # this same (healthy) connection — keep reading.
                if fr.req_id < req_id:
                    continue
                raise TornFrameError(
                    f"torn frame: response id {fr.req_id} from the "
                    f"future (sent {req_id})")
            resp = (decode_bin_response(fr.payload)
                    if fr.flags & FLAG_BIN else fr.json())
            if (not resp.get("ok") and resp.get("deadline_exceeded")
                    and resp.get("retryable")):
                # The server gave up on our stale deadline; retry with
                # what is left of OUR budget.
                raise ServerBusyError("server-side deadline exceeded")
            return resp

    def multi(self, reqs, *, deadline_s: float | None = None) -> list:
        """Batched lookups: ONE frame carries every request in ``reqs``
        (pull/score/topk dicts, same shapes as :meth:`request`), one
        frame comes back with per-request results — the per-request
        framing/syscall/CRC overhead is amortized across the batch, and
        the server merges the whole frame into one fancy-index gather
        per table. Returns the per-request response list (each entry an
        ``{"ok": ...}`` dict; item failures ride inside their entry and
        never fail siblings).

        Against a server that did not grant :data:`CAP_MULTI` (an old
        peer), falls back to sequential single requests — same results,
        PR-16 throughput."""
        reqs = list(reqs)
        if CAP_MULTI in self.caps:
            resp = self.request({"op": "multi", "reqs": reqs},
                                deadline_s=deadline_s)
            if not resp.get("ok"):
                raise WireError(
                    f"multi rejected: {resp.get('error')}")
            results = resp.get("results")
            if not isinstance(results, list) or len(results) != len(reqs):
                raise TornFrameError(
                    f"torn frame: multi returned "
                    f"{None if results is None else len(results)} "
                    f"results for {len(reqs)} requests")
            return results
        return [self.request(r, deadline_s=deadline_s) for r in reqs]
