"""Multi-tenant pods: blast-radius isolation for many models on one fleet.

:mod:`~fps_tpu.tenancy.paths` is the lint-enforced (FPS009) namespace
helper, :mod:`~fps_tpu.tenancy.manager` runs M supervised model
instances side by side with per-tenant fences/quarantine/fault scope,
and :mod:`~fps_tpu.tenancy.audit` proves zero cross-tenant writes after
a faulted run. All stdlib-only — safe in control-plane processes.
"""

from fps_tpu.tenancy.paths import (  # noqa: F401
    CKPT_DIRNAME,
    MANIFEST_FILENAME,
    OBS_DIRNAME,
    OUT_FILENAME,
    STATE_DIRNAME,
    TENANTS_DIRNAME,
    TenantPaths,
    list_tenants,
    tenants_root,
    validate_tenant_name,
)
from fps_tpu.tenancy.audit import audit_namespaces  # noqa: F401
from fps_tpu.tenancy.manager import (  # noqa: F401
    MANIFEST_SCHEMA_VERSION,
    TENANT_ENV,
    TenantManager,
    TenantSpec,
)
