"""Post-run namespace audit: prove zero cross-tenant writes.

The blast-radius contract is structural — every byte a tenant's planes
produce must land inside ``<root>/tenants/<name>/``. The audit walks a
fleet root after a (possibly faulted) run and classifies every file it
finds:

* inside a known tenant's namespace → attributed to that tenant;
* directly under the fleet root or ``tenants/`` itself (no files are
  ever legal there — only directories) → violation;
* under ``tenants/<unknown>/`` → violation (a plane invented a
  namespace no spec declared).

Chaos scenarios run this after every multi-tenant arm and carry the
result into the sweep digest, so "no cross-contamination" is evidence,
not assertion. Stdlib-only, loadable by file path.
"""

from __future__ import annotations

import os
import sys as _sys


def _load_sibling(name: str, *parts: str):
    import importlib.util as _ilu
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, *parts, name + ".py")
    spec = _ilu.spec_from_file_location(
        f"fps_tpu.tenancy.{name}" if not parts else name, path)
    mod = _ilu.module_from_spec(spec)
    _sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_paths = (_sys.modules.get("fps_tpu.tenancy.paths")
          or _load_sibling("paths"))


def audit_namespaces(root: str, tenant_names) -> dict:
    """Walk ``root`` and attribute every file to exactly one tenant.

    Returns ``{"files": N, "per_tenant": {name: count},
    "violations": [relpath, ...], "clean": bool}``. ``violations`` is
    every file that is not inside a declared tenant's namespace —
    including files under an undeclared ``tenants/<x>/`` subtree and
    loose files at the fleet root (the manager keeps no root-level
    files; all its state is per-tenant).
    """
    names = [_paths.validate_tenant_name(n) for n in tenant_names]
    tenant_dirs = {n: os.path.abspath(_paths.TenantPaths(root, n).tenant_dir)
                   for n in names}
    per_tenant = {n: 0 for n in names}
    violations = []
    total = 0
    root_abs = os.path.abspath(root)
    for dirpath, _dirnames, filenames in os.walk(root_abs):
        for fn in filenames:
            full = os.path.join(dirpath, fn)
            total += 1
            owner = None
            for n, tdir in tenant_dirs.items():
                if os.path.commonpath([tdir, full]) == tdir:
                    owner = n
                    break
            if owner is None:
                violations.append(os.path.relpath(full, root_abs))
            else:
                per_tenant[owner] += 1
    violations.sort()
    return {
        "files": total,
        "per_tenant": per_tenant,
        "violations": violations,
        "clean": not violations,
    }
