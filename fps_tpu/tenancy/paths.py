"""Tenant namespace layout: THE one place tenant paths are built.

Every plane that is tenancy-aware (the manager, the audit, the chaos
scenarios, the obs fleet rollup) derives its on-disk locations from
:class:`TenantPaths` — never by joining ``"tenants"`` / ``"ckpt"`` /
``"obs"`` string literals itself. Lint rule FPS009
(:mod:`fps_tpu.analysis.lint`) enforces this: a namespace-flavored
literal in a path-building call outside this module flags. The payoff is
the blast-radius contract — if no plane can even *spell* a neighbor's
namespace, one tenant's fault cannot write into another's state.

Layout under a fleet root ``R``::

    R/tenants/<name>/tenant.json   manifest (weight, seed, SLO overrides)
    R/tenants/<name>/ckpt/         snapshots, sidecars, fleet/ fences
    R/tenants/<name>/obs/          events-p*.jsonl, journal-*.jsonl
    R/tenants/<name>/state/        supervisor state/journal/heartbeat/logs
    R/tenants/<name>/out.npz       the tenant's exported weights

Stdlib-only and importable both as ``fps_tpu.tenancy.paths`` and by bare
file path (the :mod:`fps_tpu.supervise.pod` convention) — it must never
drag jax into a control-plane process.
"""

from __future__ import annotations

import dataclasses
import os
import re

# Mirrored (with a mirror test) in fps_tpu/obs/fleet.py, which is loaded
# by file path and cannot import this module.
TENANTS_DIRNAME = "tenants"
MANIFEST_FILENAME = "tenant.json"
CKPT_DIRNAME = "ckpt"
OBS_DIRNAME = "obs"
STATE_DIRNAME = "state"
OUT_FILENAME = "out.npz"

_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,63}$")


def validate_tenant_name(name: str) -> str:
    """Return ``name`` if it is a legal tenant name, else raise.

    Names become directory components and journal/metric labels, so the
    grammar is deliberately narrow: lowercase alphanumerics, ``-`` and
    ``_``, at most 64 chars, no leading separator. Anything that could
    escape the namespace (``..``, ``/``, empty) is rejected here, once.
    """
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"illegal tenant name {name!r}: must match {_NAME_RE.pattern}")
    return name


def tenants_root(root: str) -> str:
    """``<root>/tenants`` — the directory holding all tenant namespaces."""
    return os.path.join(root, TENANTS_DIRNAME)


@dataclasses.dataclass(frozen=True)
class TenantPaths:
    """All on-disk locations for one tenant under one fleet root."""

    root: str
    name: str

    def __post_init__(self):
        validate_tenant_name(self.name)

    @property
    def tenant_dir(self) -> str:
        return os.path.join(tenants_root(self.root), self.name)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.tenant_dir, MANIFEST_FILENAME)

    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.tenant_dir, CKPT_DIRNAME)

    @property
    def obs_dir(self) -> str:
        return os.path.join(self.tenant_dir, OBS_DIRNAME)

    @property
    def state_dir(self) -> str:
        return os.path.join(self.tenant_dir, STATE_DIRNAME)

    @property
    def out_path(self) -> str:
        return os.path.join(self.tenant_dir, OUT_FILENAME)

    def ensure(self) -> "TenantPaths":
        """Create the namespace directories (idempotent)."""
        for d in (self.ckpt_dir, self.obs_dir, self.state_dir):
            os.makedirs(d, exist_ok=True)
        return self

    def owns(self, path: str) -> bool:
        """True iff ``path`` lies inside this tenant's namespace."""
        tenant = os.path.abspath(self.tenant_dir)
        return os.path.commonpath(
            [tenant, os.path.abspath(path)]) == tenant


def list_tenants(root: str) -> list[str]:
    """Tenant names present under ``root`` (sorted; [] if none)."""
    base = tenants_root(root)
    try:
        entries = os.listdir(base)
    except OSError:
        return []
    return sorted(n for n in entries
                  if _NAME_RE.match(n)
                  and os.path.isdir(os.path.join(base, n)))
