"""TenantManager: M independent model instances on one pod, zero shared fate.

A :class:`TenantSpec` names one model instance — its child argv (with
``{ckpt}``/``{obs}``/``{state}``/``{out}``/``{name}`` placeholders the
manager resolves through :class:`~fps_tpu.tenancy.paths.TenantPaths`),
its hot-tier arbitration weight, seed, extra child env, and SLO target
overrides. :class:`TenantManager` runs every spec under its own
:class:`~fps_tpu.supervise.supervisor.RunSupervisor` in its own thread,
with:

* a private namespace for everything it writes (checkpoints, sidecars,
  obs streams, supervisor state, exported weights) — built ONLY through
  ``TenantPaths`` (lint rule FPS009);
* a private fencing epoch: ``pod_fence.json`` lives in the tenant's own
  checkpoint dir and ``FPS_TPU_POD_EPOCH`` is injected per child, so one
  tenant's epoch bump / ``StaleEpochError`` cannot regress or advance a
  neighbor's fence;
* private quarantine state: the supervisor's poison-chunk presets live
  in the tenant's own ``state/supervisor_state.json``;
* private fault scope: per-spec env is the ONLY way injection reaches a
  child, so a ``FPS_TPU_FAULTFS`` schedule in tenant A's spec is
  invisible to tenant B by construction.

The isolation proof lives in :mod:`fps_tpu.testing.tenant_demo` — every
non-injected tenant must finish bit-identical to its solo run.

Stdlib-only: the supervise modules are resolved from ``sys.modules``
when the package is imported normally, by file path otherwise (the
:mod:`fps_tpu.supervise.pod` convention), so a control-plane process
never drags jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys as _sys
import threading


def _load_sibling(name: str, package: str, *parts: str):
    import importlib.util as _ilu
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.abspath(os.path.join(here, *parts, name + ".py"))
    spec = _ilu.spec_from_file_location(f"fps_tpu.{package}.{name}", path)
    mod = _ilu.module_from_spec(spec)
    # Pre-register so dataclasses in the module resolve their own module
    # (required on 3.10 for modules executed from a file location).
    _sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_paths = (_sys.modules.get("fps_tpu.tenancy.paths")
          or _load_sibling("paths", "tenancy"))
_sup = (_sys.modules.get("fps_tpu.supervise.supervisor")
        or _load_sibling("supervisor", "supervise", os.pardir, "supervise"))
_child = (_sys.modules.get("fps_tpu.supervise.child")
          or _load_sibling("child", "supervise", os.pardir, "supervise"))

TENANT_ENV = "FPS_TPU_TENANT"
MANIFEST_SCHEMA_VERSION = 1
# Placeholders a spec's argv/watch entries may carry; resolved against
# the tenant's TenantPaths before anything runs.
_PLACEHOLDERS = ("{ckpt}", "{obs}", "{state}", "{out}", "{name}", "{root}")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant: a workload command plus its arbitration/SLO identity.

    Args:
      name: tenant name (namespace component; validated).
      cmd: child argv template. Entries may embed ``{ckpt}``, ``{obs}``,
        ``{state}``, ``{out}``, ``{name}``, ``{root}`` — resolved to the
        tenant's namespaced locations.
      weight: hot-tier replica-budget arbitration weight (> 0); consumed
        by :func:`fps_tpu.tiering.planner.arbitrate_replica_budget`.
      seed: workload seed, recorded in the manifest for solo replays.
      env: extra child environment — also the per-tenant fault-injection
        scope (``FPS_TPU_FAULTFS`` here reaches ONLY this tenant).
      slo: SLO target overrides, ``{slo_name: target}``; consumed by the
        obs fleet rollup.
      watch: extra supervisor liveness watch globs (placeholders ok).
    """

    name: str
    cmd: tuple = ()
    weight: float = 1.0
    seed: int = 0
    env: dict = dataclasses.field(default_factory=dict)
    slo: dict = dataclasses.field(default_factory=dict)
    watch: tuple = ()

    def __post_init__(self):
        _paths.validate_tenant_name(self.name)
        if not self.cmd:
            raise ValueError(f"tenant {self.name!r}: empty cmd")
        if not (isinstance(self.weight, (int, float)) and self.weight > 0):
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, "
                f"got {self.weight!r}")
        object.__setattr__(self, "cmd", tuple(self.cmd))
        object.__setattr__(self, "watch", tuple(self.watch))


class TenantManager:
    """Run M TenantSpecs side by side with per-tenant blast radius.

    thread-safety: ``run()`` starts one thread per tenant; each thread
    touches only ITS tenant's supervisor and writes only its own key of
    the shared digests dict (distinct-key dict writes are atomic under
    CPython), and ``run()`` joins every thread before reading them —
    there is no other cross-thread state.
    """

    def __init__(self, root: str, specs, *,
                 config=None, base_env: dict | None = None):
        specs = tuple(specs)
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        self.root = root
        self.specs = {s.name: s for s in specs}
        self.config = config or _sup.SupervisorConfig()
        self.base_env = dict(base_env or {})
        self.paths = {s.name: _paths.TenantPaths(root, s.name)
                      for s in specs}
        self._digests: dict = {}

    # -- namespace + manifest ------------------------------------------

    def prepare(self) -> None:
        """Create every namespace, write manifests, seed fences at epoch 1."""
        for name, spec in self.specs.items():
            tp = self.paths[name].ensure()
            manifest = {
                "schema": MANIFEST_SCHEMA_VERSION,
                "name": name,
                "weight": spec.weight,
                "seed": spec.seed,
                "slo": dict(spec.slo),
            }
            tmp = tp.manifest_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, tp.manifest_path)
            if _child.read_fence(tp.ckpt_dir) is None:
                _child.write_fence(tp.ckpt_dir, 1, 0)

    # -- per-tenant fencing epochs -------------------------------------

    def fence_epoch(self, name: str) -> int:
        """Current fencing epoch of ONE tenant (0 if unfenced)."""
        fence = _child.read_fence(self.paths[name].ckpt_dir)
        return int(fence["min_epoch"]) if fence else 0

    def bump_fence(self, name: str, *, step: int = 0) -> int:
        """Advance ONE tenant's fencing epoch; neighbors are untouched."""
        epoch = self.fence_epoch(name) + 1
        _child.write_fence(self.paths[name].ckpt_dir, epoch, step)
        return epoch

    # -- running -------------------------------------------------------

    def _resolve(self, spec, text: str) -> str:
        tp = self.paths[spec.name]
        for key, val in (("{ckpt}", tp.ckpt_dir), ("{obs}", tp.obs_dir),
                         ("{state}", tp.state_dir), ("{out}", tp.out_path),
                         ("{name}", spec.name), ("{root}", tp.root)):
            text = text.replace(key, val)
        return text

    def supervisor(self, name: str):
        """Build the per-tenant RunSupervisor (state in the tenant's
        namespace, fence epoch + tenant identity in the child env)."""
        spec = self.specs[name]
        tp = self.paths[name]
        env = dict(self.base_env)
        env.update(spec.env)
        env[TENANT_ENV] = name
        env[_child.POD_EPOCH_ENV] = str(max(self.fence_epoch(name), 1))
        cmd = [self._resolve(spec, a) for a in spec.cmd]
        watch = tuple(self._resolve(spec, w) for w in spec.watch)
        return _sup.RunSupervisor(
            cmd, state_dir=tp.state_dir, config=self.config,
            watch=watch, env=env)

    def run(self) -> dict:
        """Run every tenant concurrently; return ``{name: digest}``.

        One tenant exhausting its restarts (digest ``success: False``)
        or raising does not interrupt the others — its entry records the
        failure and every other tenant runs to its own conclusion.
        """
        self.prepare()
        digests: dict = {}

        def _one(name: str):
            try:
                digests[name] = self.supervisor(name).run()
            except Exception as exc:  # isolation: never kill neighbors
                digests[name] = {"success": False,
                                 "error": f"{type(exc).__name__}: {exc}"}

        threads = [threading.Thread(target=_one, args=(n,),
                                    name=f"tenant-{n}", daemon=True)
                   for n in self.specs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._digests = digests
        return digests

    def journal_path(self, name: str) -> str:
        """The tenant's supervisor journal (for recovery-time extraction)."""
        return os.path.join(self.paths[name].state_dir,
                            _sup.JOURNAL_FILENAME)
